"""Typed wire codec for the storage RPC boundary.

Reference: /root/reference/store/tikv/tikvrpc/tikvrpc.go:31-53 (the typed
CmdType envelope) and the vendored kvproto/tipb protobufs that define the
reference's closed cross-process contract. This module is the tpu build's
equivalent of that contract: a self-describing tag-length-value encoding
over a CLOSED registry of struct/enum/error types. Nothing outside the
registry can cross the wire, decoding never executes arbitrary code (no
pickle), and every length/tag/id is validated so malformed frames raise
`WireError` instead of corrupting state (fuzzed in tests/test_wire.py).

Layout (little-endian):
  frame  = u32 payload_len | u8 status | payload
  value  = u8 tag | body
  varint = LEB128, max 10 bytes

Value tags:
  0 NONE   1 TRUE    2 FALSE   3 INT(zigzag varint)   4 FLOAT(8B IEEE)
  5 BYTES  6 STR     7 LIST    8 TUPLE   9 DICT
  10 NDARRAY(u8 dtype, varint n, raw buf)   11 OBJARR(varint n, items)
  12 STRUCT(u16 id, varint nfields, values)
  13 ENUM(u16 id, value)        14 ERROR(u16 id, args tuple, msg str)
  15 FNSPEC(str name)           16 BIGINT(signed big-endian bytes)
  17 DECIMAL(str)

Chunk columns ride as NDARRAY (fixed-width lanes: one raw memcpy-able
buffer, the same buffer `jax.device_put` consumes) or OBJARR (varlen).
"""

from __future__ import annotations

import struct
from decimal import Decimal
from enum import IntEnum

import numpy as np

__all__ = ["Cmd", "WireError", "encode", "decode",
           "encode_frame", "decode_frame_payload",
           "STATUS_OK", "STATUS_ERR", "STATUS_OK_TRACED",
           "STATUS_STREAM_FRAME", "STATUS_STREAM_END", "STATUS_CREDIT",
           "FLAG_TRACE", "FLAG_ORIGIN",
           "MAX_STREAM_CREDIT", "StreamReader", "CreditGate"]


class WireError(Exception):
    """Malformed or out-of-contract wire data."""


class Cmd(IntEnum):
    """Command enum (ref: tikvrpc.go:31-53 CmdType)."""

    PING = 0
    # transactional KV
    KV_GET = 1
    KV_SCAN = 2
    KV_PREWRITE = 3
    KV_COMMIT = 4
    KV_CLEANUP = 5
    KV_BATCH_GET = 6
    KV_BATCH_ROLLBACK = 7
    KV_SCAN_LOCK = 8
    KV_RESOLVE_LOCK = 9
    KV_GC = 10
    KV_DELETE_RANGE = 11
    # raw KV
    RAW_GET = 20
    RAW_BATCH_GET = 21
    RAW_PUT = 22
    RAW_BATCH_PUT = 23
    RAW_DELETE = 24
    RAW_DELETE_RANGE = 25
    RAW_SCAN = 26
    # coprocessor
    COP = 40
    # streaming coprocessor: multi-frame reply with credit flow control
    # (ref: CmdCopStream, store/tikv/coprocessor.go:547-555)
    COP_STREAM = 41
    # debug / admin
    MVCC_BY_KEY = 50
    MVCC_BY_START_TS = 51
    SPLIT_REGION = 52
    # PD role (TSO + region routing) served by the storage process
    TSO = 60
    REGION_BY_KEY = 61
    REGIONS_SNAPSHOT = 62
    SPLIT = 63
    SPLIT_TABLE = 64
    BULK_IMPORT = 65
    # replication control (primary/backup log shipping)
    REPL_HELLO = 70
    REPL_APPLY = 71
    REPL_SNAPSHOT = 72
    REPL_PROMOTE = 73
    REPL_INSTALL = 74
    # fleet cache coherence: one round trip returns the engine's
    # freshness meta (data_version / max_commit_ts / lock state) plus
    # the delta-journal window (fill_ts, read_ts] for one region range,
    # so a remote SQL server patches its resident chunk/HBM blocks in
    # place instead of re-colding on every remote read (store/delta.py)
    JOURNAL_WINDOW = 80


# method-name <-> Cmd mapping used by the RPC layer (the shim's python
# methods keep their names; the wire carries the enum)
CMD_BY_METHOD = {
    "ping": Cmd.PING,
    "kv_get": Cmd.KV_GET, "kv_scan": Cmd.KV_SCAN,
    "kv_prewrite": Cmd.KV_PREWRITE, "kv_commit": Cmd.KV_COMMIT,
    "kv_cleanup": Cmd.KV_CLEANUP, "kv_batch_get": Cmd.KV_BATCH_GET,
    "kv_batch_rollback": Cmd.KV_BATCH_ROLLBACK,
    "kv_scan_lock": Cmd.KV_SCAN_LOCK,
    "kv_resolve_lock": Cmd.KV_RESOLVE_LOCK, "kv_gc": Cmd.KV_GC,
    "kv_delete_range": Cmd.KV_DELETE_RANGE,
    "raw_get": Cmd.RAW_GET, "raw_batch_get": Cmd.RAW_BATCH_GET,
    "raw_put": Cmd.RAW_PUT, "raw_batch_put": Cmd.RAW_BATCH_PUT,
    "raw_delete": Cmd.RAW_DELETE,
    "raw_delete_range": Cmd.RAW_DELETE_RANGE, "raw_scan": Cmd.RAW_SCAN,
    "coprocessor": Cmd.COP,
    "coprocessor_stream": Cmd.COP_STREAM,
    "mvcc_by_key": Cmd.MVCC_BY_KEY,
    "mvcc_by_start_ts": Cmd.MVCC_BY_START_TS,
    "split_region": Cmd.SPLIT_REGION,
    "tso": Cmd.TSO, "region_by_key": Cmd.REGION_BY_KEY,
    "regions_snapshot": Cmd.REGIONS_SNAPSHOT,
    "split": Cmd.SPLIT, "split_table": Cmd.SPLIT_TABLE,
    "bulk_import": Cmd.BULK_IMPORT,
    "repl_hello": Cmd.REPL_HELLO, "repl_apply": Cmd.REPL_APPLY,
    "repl_snapshot": Cmd.REPL_SNAPSHOT,
    "repl_promote": Cmd.REPL_PROMOTE,
    "repl_install": Cmd.REPL_INSTALL,
    "journal_window": Cmd.JOURNAL_WINDOW,
}
METHOD_BY_CMD = {v: k for k, v in CMD_BY_METHOD.items()}

# -- tags ---------------------------------------------------------------------

_T_NONE, _T_TRUE, _T_FALSE, _T_INT, _T_FLOAT = 0, 1, 2, 3, 4
_T_BYTES, _T_STR, _T_LIST, _T_TUPLE, _T_DICT = 5, 6, 7, 8, 9
_T_NDARRAY, _T_OBJARR, _T_STRUCT, _T_ENUM, _T_ERROR = 10, 11, 12, 13, 14
_T_FNSPEC, _T_BIGINT, _T_DECIMAL = 15, 16, 17

_MAX_DEPTH = 64
_MAX_LEN = 1 << 31

# fixed-width lanes allowed in NDARRAY (codes are wire-stable)
_DTYPES = {0: np.dtype(np.int64), 1: np.dtype(np.float64),
           2: np.dtype(np.int32), 3: np.dtype(np.float32),
           4: np.dtype(np.bool_), 5: np.dtype(np.uint8),
           6: np.dtype(np.uint64)}
_DTYPE_CODES = {v: k for k, v in _DTYPES.items()}

_INT64_MIN, _INT64_MAX = -(1 << 63), (1 << 63) - 1


# -- registries (append-only ids: the wire contract) --------------------------

_STRUCTS: dict[int, tuple] = {}      # id -> (cls, field_names, rebuild)
_STRUCT_IDS: dict[type, int] = {}
_ENUMS: dict[int, type] = {}
_ENUM_IDS: dict[type, int] = {}
_ERRORS: dict[int, type] = {}
_ERROR_IDS: dict[type, int] = {}


def _reg_struct(sid: int, cls, fields=None, rebuild=None):
    if fields is None:
        fields = [f.name for f in cls.__dataclass_fields__.values()] \
            if hasattr(cls, "__dataclass_fields__") else None
    if fields is None:
        raise TypeError(f"{cls} needs explicit fields")
    if rebuild is None:
        def rebuild(vals, cls=cls):
            return cls(*vals)
    _STRUCTS[sid] = (cls, fields, rebuild)
    _STRUCT_IDS[cls] = sid


def _reg_enum(eid: int, cls):
    _ENUMS[eid] = cls
    _ENUM_IDS[cls] = eid


def _reg_error(eid: int, cls):
    _ERRORS[eid] = cls
    _ERROR_IDS[cls] = eid


def _install_registry():
    """One closed list; ids are stable wire contract, append-only."""
    from tidb_tpu import kv
    from tidb_tpu.chunk import Chunk, Column
    from tidb_tpu.expression.agg import AggDesc, AggFunc
    from tidb_tpu.expression.core import (ColumnRef, Constant, Op,
                                          ScalarFunc)
    from tidb_tpu.mockstore.cluster import Region, Store
    from tidb_tpu.mockstore.rpc import RegionCtx, TimeoutError_
    from tidb_tpu.plan.physical import CopPlan
    from tidb_tpu.ranger import DatumRange
    from tidb_tpu.schema.model import (ColumnInfo, DBInfo, IndexInfo,
                                       SchemaState, TableInfo)
    from tidb_tpu.sqltypes import FieldType, TypeCode

    # structs (ids 1..)
    _reg_struct(1, kv.KVRange)
    _reg_struct(2, kv.Mutation)
    _reg_struct(3, kv.LockInfo)
    _reg_struct(4, kv.CopRequest)
    _reg_struct(5, kv.CopResponse)
    _reg_struct(6, RegionCtx,
                fields=["region_id", "version", "conf_ver", "store_id"])
    _reg_struct(7, Region)
    _reg_struct(8, Store)
    _reg_struct(9, CopPlan)
    _reg_struct(10, TableInfo)
    _reg_struct(11, ColumnInfo)
    _reg_struct(12, IndexInfo)
    _reg_struct(13, DBInfo)
    _reg_struct(14, FieldType)
    _reg_struct(15, AggDesc)
    _reg_struct(16, ColumnRef)
    _reg_struct(17, Constant)
    _reg_struct(18, DatumRange)

    def _rebuild_scalarfunc(vals):
        op, args, extra, ft = vals
        f = ScalarFunc.__new__(ScalarFunc)
        f.op, f.args, f.extra, f.ft = op, list(args), extra, ft
        return f

    _reg_struct(19, ScalarFunc, fields=["op", "args", "extra", "ft"],
                rebuild=_rebuild_scalarfunc)

    def _rebuild_column(vals):
        ft, data, valid = vals
        return Column(ft, data, valid)

    _reg_struct(20, Column, fields=["ft", "data", "valid"],
                rebuild=_rebuild_column)
    _reg_struct(21, Chunk, fields=["columns"],
                rebuild=lambda vals: Chunk(vals[0]))

    from tidb_tpu.ops.hashagg import GroupResult
    _reg_struct(22, GroupResult)

    from tidb_tpu.store.stream import StreamFrame
    _reg_struct(25, StreamFrame, fields=["chunk", "range", "last"])

    # MVCC engine internals: cross the wire only in REPL_SNAPSHOT state
    # transfer (primary -> attaching backup)
    from tidb_tpu.mockstore.mvcc import WriteType, _Entry, _Lock
    _reg_struct(23, _Lock)
    _reg_struct(24, _Entry)
    _reg_enum(9, WriteType)

    # enums (ids 1..)
    _reg_enum(1, kv.MutationOp)
    _reg_enum(2, kv.ReqType)
    _reg_enum(3, kv.Priority)
    _reg_enum(4, kv.IsolationLevel)
    _reg_enum(5, Op)
    _reg_enum(6, AggFunc)
    _reg_enum(7, TypeCode)
    _reg_enum(8, SchemaState)

    # errors (ids 1..); ctor args come from each class's __reduce__
    _reg_error(1, kv.KVError)
    _reg_error(2, kv.NotFoundError)
    _reg_error(3, kv.RetryableError)
    _reg_error(4, kv.GCTooEarlyError)
    _reg_error(5, kv.SchemaChangedError)
    _reg_error(6, kv.KeyLockedError)
    _reg_error(7, kv.WriteConflictError)
    _reg_error(8, kv.RegionError)
    _reg_error(9, kv.NotLeaderError)
    _reg_error(10, kv.EpochNotMatchError)
    _reg_error(11, kv.StoreUnavailableError)
    _reg_error(12, kv.ServerBusyError)
    _reg_error(13, TimeoutError_)
    _reg_error(14, kv.StreamInterruptedError)


_installed = False


def _ensure_registry():
    global _installed
    if not _installed:
        _install_registry()
        _installed = True


# -- encoding -----------------------------------------------------------------

def _put_varint(out: bytearray, n: int) -> None:
    if n < 0:
        raise WireError("negative length")
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _zigzag(n: int) -> int:
    return (n << 1) ^ (n >> 63) if n >= 0 else ((-n) << 1) - 1


def _unzigzag(n: int) -> int:
    return (n >> 1) if not (n & 1) else -((n + 1) >> 1)


def _enc(out: bytearray, v, depth: int) -> None:
    if depth > _MAX_DEPTH:
        raise WireError("nesting too deep")
    if v is None:
        out.append(_T_NONE)
    elif v is True:
        out.append(_T_TRUE)
    elif v is False:
        out.append(_T_FALSE)
    elif isinstance(v, np.bool_):
        out.append(_T_TRUE if v else _T_FALSE)
    elif isinstance(v, (int, np.integer)) and not isinstance(v, IntEnum):
        v = int(v)
        if _INT64_MIN <= v <= _INT64_MAX:
            out.append(_T_INT)
            _put_varint(out, _zigzag(v))
        else:
            out.append(_T_BIGINT)
            nb = (v.bit_length() + 8) // 8
            b = v.to_bytes(nb, "big", signed=True)
            _put_varint(out, len(b))
            out += b
    elif isinstance(v, (float, np.floating)):
        out.append(_T_FLOAT)
        out += struct.pack("<d", float(v))
    elif isinstance(v, bytes):
        out.append(_T_BYTES)
        _put_varint(out, len(v))
        out += v
    elif isinstance(v, str):
        b = v.encode("utf8")
        out.append(_T_STR)
        _put_varint(out, len(b))
        out += b
    elif isinstance(v, Decimal):
        b = str(v).encode("ascii")
        out.append(_T_DECIMAL)
        _put_varint(out, len(b))
        out += b
    elif isinstance(v, np.ndarray):
        if v.dtype == np.dtype(object):
            out.append(_T_OBJARR)
            _put_varint(out, len(v))
            for x in v:
                _enc(out, x, depth + 1)
        else:
            code = _DTYPE_CODES.get(v.dtype)
            if code is None:
                raise WireError(f"dtype {v.dtype} not in wire contract")
            if v.ndim != 1:
                v = np.ascontiguousarray(v).reshape(-1)
            out.append(_T_NDARRAY)
            out.append(code)
            _put_varint(out, len(v))
            out += np.ascontiguousarray(v).tobytes()
    elif isinstance(v, list):
        out.append(_T_LIST)
        _put_varint(out, len(v))
        for x in v:
            _enc(out, x, depth + 1)
    elif isinstance(v, tuple):
        out.append(_T_TUPLE)
        _put_varint(out, len(v))
        for x in v:
            _enc(out, x, depth + 1)
    elif isinstance(v, dict):
        out.append(_T_DICT)
        _put_varint(out, len(v))
        for k, x in v.items():
            _enc(out, k, depth + 1)
            _enc(out, x, depth + 1)
    elif isinstance(v, BaseException):
        _ensure_registry()
        eid = _ERROR_IDS.get(type(v))
        if eid is None:
            # out-of-registry exception: degrade to KVError with repr —
            # never ship arbitrary reconstruction info
            from tidb_tpu import kv
            eid = _ERROR_IDS[kv.KVError]
            args = (f"{type(v).__name__}: {v}",)
        else:
            red = v.__reduce__()
            args = red[1] if isinstance(red, tuple) and len(red) >= 2 \
                else (str(v),)
        out.append(_T_ERROR)
        out += struct.pack("<H", eid)
        _enc(out, tuple(args), depth + 1)
    else:
        _ensure_registry()
        cls = type(v)
        if cls in _ENUM_IDS:
            out.append(_T_ENUM)
            out += struct.pack("<H", _ENUM_IDS[cls])
            _enc(out, v.value, depth + 1)
            return
        sid = _STRUCT_IDS.get(cls)
        if sid is not None:
            _cls, fields, _rb = _STRUCTS[sid]
            out.append(_T_STRUCT)
            out += struct.pack("<H", sid)
            _put_varint(out, len(fields))
            for f in fields:
                _enc(out, getattr(v, f), depth + 1)
            return
        # FnSpec crosses by name (host_filter pushdown)
        from tidb_tpu.expression.builtins import FnSpec
        if isinstance(v, FnSpec):
            b = v.name.encode("utf8")
            out.append(_T_FNSPEC)
            _put_varint(out, len(b))
            out += b
            return
        raise WireError(
            f"type {cls.__module__}.{cls.__name__} not in wire contract")


def encode(v) -> bytes:
    out = bytearray()
    _enc(out, v, 0)
    return bytes(out)


# -- decoding -----------------------------------------------------------------

class _Reader:
    __slots__ = ("buf", "pos", "n")

    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0
        self.n = len(buf)

    def take(self, k: int) -> bytes:
        if k < 0 or self.pos + k > self.n:
            raise WireError("truncated frame")
        b = self.buf[self.pos:self.pos + k]
        self.pos += k
        return b

    def u8(self) -> int:
        return self.take(1)[0]

    def u16(self) -> int:
        return struct.unpack("<H", self.take(2))[0]

    def varint(self) -> int:
        out = 0
        shift = 0
        while True:
            if shift > 63:
                raise WireError("varint too long")
            b = self.u8()
            out |= (b & 0x7F) << shift
            if not (b & 0x80):
                return out
            shift += 7


def _dec(r: _Reader, depth: int):
    if depth > _MAX_DEPTH:
        raise WireError("nesting too deep")
    tag = r.u8()
    if tag == _T_NONE:
        return None
    if tag == _T_TRUE:
        return True
    if tag == _T_FALSE:
        return False
    if tag == _T_INT:
        return _unzigzag(r.varint())
    if tag == _T_FLOAT:
        return struct.unpack("<d", r.take(8))[0]
    if tag == _T_BYTES:
        return r.take(r.varint())
    if tag == _T_STR:
        try:
            return r.take(r.varint()).decode("utf8")
        except UnicodeDecodeError as e:
            raise WireError(f"bad utf8: {e}") from None
    if tag == _T_DECIMAL:
        try:
            return Decimal(r.take(r.varint()).decode("ascii"))
        except Exception as e:
            raise WireError(f"bad decimal: {e}") from None
    if tag == _T_BIGINT:
        return int.from_bytes(r.take(r.varint()), "big", signed=True)
    if tag in (_T_LIST, _T_TUPLE):
        k = r.varint()
        if k > r.n - r.pos:      # each element is >= 1 byte
            raise WireError("length exceeds frame")
        items = [_dec(r, depth + 1) for _ in range(k)]
        return items if tag == _T_LIST else tuple(items)
    if tag == _T_DICT:
        k = r.varint()
        if k * 2 > r.n - r.pos:
            raise WireError("length exceeds frame")
        out = {}
        for _ in range(k):
            key = _dec(r, depth + 1)
            try:
                out[key] = _dec(r, depth + 1)
            except TypeError as e:
                raise WireError(f"unhashable dict key: {e}") from None
        return out
    if tag == _T_NDARRAY:
        code = r.u8()
        dt = _DTYPES.get(code)
        if dt is None:
            raise WireError(f"unknown dtype code {code}")
        k = r.varint()
        nbytes = k * dt.itemsize
        if nbytes > r.n - r.pos:
            raise WireError("array exceeds frame")
        return np.frombuffer(r.take(nbytes), dtype=dt).copy()
    if tag == _T_OBJARR:
        k = r.varint()
        if k > r.n - r.pos:
            raise WireError("length exceeds frame")
        out = np.empty(k, dtype=object)
        for i in range(k):
            out[i] = _dec(r, depth + 1)
        return out
    if tag == _T_STRUCT:
        _ensure_registry()
        sid = r.u16()
        ent = _STRUCTS.get(sid)
        if ent is None:
            raise WireError(f"unknown struct id {sid}")
        cls, fields, rebuild = ent
        k = r.varint()
        if k != len(fields):
            raise WireError(
                f"struct {cls.__name__}: {k} fields, want {len(fields)}")
        vals = [_dec(r, depth + 1) for _ in range(k)]
        try:
            return rebuild(vals)
        except WireError:
            raise
        except Exception as e:
            raise WireError(
                f"struct {cls.__name__} rebuild failed: {e}") from None
    if tag == _T_ENUM:
        _ensure_registry()
        eid = r.u16()
        cls = _ENUMS.get(eid)
        if cls is None:
            raise WireError(f"unknown enum id {eid}")
        try:
            return cls(_dec(r, depth + 1))
        except ValueError as e:
            raise WireError(str(e)) from None
    if tag == _T_ERROR:
        _ensure_registry()
        eid = r.u16()
        cls = _ERRORS.get(eid)
        if cls is None:
            raise WireError(f"unknown error id {eid}")
        args = _dec(r, depth + 1)
        if not isinstance(args, tuple):
            raise WireError("error args must be a tuple")
        try:
            return cls(*args)
        except Exception as e:
            raise WireError(
                f"error {cls.__name__} rebuild failed: {e}") from None
    if tag == _T_FNSPEC:
        try:
            name = r.take(r.varint()).decode("utf8")
        except UnicodeDecodeError as e:
            raise WireError(f"bad utf8: {e}") from None
        from tidb_tpu.expression.builtins import REGISTRY
        spec = REGISTRY.get(name)
        if spec is None:
            raise WireError(f"unknown builtin {name!r}")
        return spec
    raise WireError(f"unknown tag {tag}")


def decode(buf: bytes):
    r = _Reader(buf)
    v = _dec(r, 0)
    if r.pos != r.n:
        raise WireError(f"{r.n - r.pos} trailing bytes")
    return v


# -- frame helpers ------------------------------------------------------------

def encode_frame(status: int, payload: bytes) -> bytes:
    if len(payload) + 1 > _MAX_LEN:
        raise WireError("frame too large")
    return struct.pack("<IB", len(payload) + 1, status) + payload


def decode_frame_payload(buf: bytes):
    """Decode a received payload, turning any codec error into WireError."""
    try:
        return decode(buf)
    except WireError:
        raise
    except Exception as e:   # noqa: BLE001 — decoder must never crash caller
        raise WireError(f"malformed frame: {e}") from None


# -- streamed replies (COP_STREAM) --------------------------------------------
#
# A COP_STREAM request opens a stream on the connection: the server
# answers with zero or more STATUS_STREAM_FRAME frames (each payload a
# StreamFrame, struct id 25), terminated by STATUS_STREAM_END (normal) or
# STATUS_ERR (typed error; the stream is over, the connection is back in
# request/response state). Flow control is credit-based: the request
# carries an initial window of N frames; the server decrements per frame
# sent and BLOCKS at zero until the client ships a STATUS_CREDIT frame
# (payload: int grant) — a slow consumer backpressures the server instead
# of growing a buffer on either side. Both directions are validated by
# the state machines below; any protocol violation (frame after END,
# more frames outstanding than granted, a non-stream status mid-stream,
# a malformed grant) raises WireError LOUDLY — never deadlocks, never
# desynchronizes silently. Ref: the grpc server-streaming contract of
# CmdCopStream (store/tikv/coprocessor.go:547-555) + tikvrpc.go.

STATUS_OK = 0
STATUS_ERR = 1
STATUS_OK_TRACED = 2   # payload = (result, span-tree dict)
STATUS_STREAM_FRAME = 3
STATUS_STREAM_END = 4
STATUS_CREDIT = 5

# request-flags vocabulary (the optional 4th element of the request
# envelope — cross-process metadata, never command arguments):
#   FLAG_TRACE:  bool — the caller is traced; run the handler under a
#       local "storage:<method>" root and ship the finished tree back
#       (STATUS_OK_TRACED / the stream END frame).
#   FLAG_ORIGIN: dict — trace.origin() of the calling STATEMENT:
#       {"trace_id": fleet-unique id, "sampled": bool, "forced": bool,
#        "member": originating member id}. The server maps sampled/
#       forced onto its local root and stamps anything it retains with
#       origin_trace_id/origin_member, so store-plane ring records
#       join back to the SQL statement that caused them.
FLAG_TRACE = "trace"
FLAG_ORIGIN = "origin"

MAX_STREAM_CREDIT = 1024


class StreamReader:
    """Client-side validation of one streamed reply.

    feed(status, payload) -> ("frame", StreamFrame) | ("end", None);
    typed server errors re-raise in the caller. Tracks the credit ledger:
    the server exceeding the granted window is a protocol violation
    (it proves the peer ignores backpressure) and fails loudly."""

    def __init__(self, credit: int):
        if not (1 <= credit <= MAX_STREAM_CREDIT):
            raise WireError(f"bad credit window {credit!r}")
        self.granted = credit
        self.consumed = 0
        self.done = False

    def grant(self, n: int = 1) -> None:
        self.granted += n

    def feed(self, status: int, payload: bytes):
        if self.done:
            raise WireError("frame after stream end")
        if status == STATUS_STREAM_END:
            self.done = True
            # END may carry the server's span tree (trace propagation)
            return ("end", decode_frame_payload(payload)
                    if payload else None)
        if status == STATUS_ERR:
            self.done = True
            err = decode_frame_payload(payload)
            if isinstance(err, BaseException):
                raise err
            raise WireError(f"stream error: {err!r}")
        if status != STATUS_STREAM_FRAME:
            # e.g. a STATUS_OK of an interleaved plain reply: streams own
            # the connection until END — anything else is corruption
            raise WireError(f"unexpected status {status} mid-stream")
        self.consumed += 1
        if self.consumed > self.granted:
            raise WireError(
                f"credit violation: {self.consumed} frames received, "
                f"{self.granted} granted")
        frame = decode_frame_payload(payload)
        from tidb_tpu import kv as _kv
        from tidb_tpu.store.stream import StreamFrame
        if not isinstance(frame, StreamFrame):
            raise WireError(
                f"stream frame payload is {type(frame).__name__}, "
                "want StreamFrame")
        # field-shape validation: consumers dereference range.start/.end
        # and branch on last — corruption must fail HERE as WireError,
        # not as an AttributeError deep in the resume logic
        if not (isinstance(frame.range, _kv.KVRange) and
                isinstance(frame.range.start, bytes) and
                isinstance(frame.range.end, bytes) and
                isinstance(frame.last, bool)):
            raise WireError("malformed StreamFrame fields")
        return ("frame", frame)


class CreditGate:
    """Server-side credit ledger: consume() per frame sent; when the
    window is exhausted the serving loop blocks reading grant frames and
    feeds them through feed_grant(), which validates them."""

    def __init__(self, credit: int):
        if not (isinstance(credit, int) and not isinstance(credit, bool)
                and 1 <= credit <= MAX_STREAM_CREDIT):
            raise WireError(f"bad credit window {credit!r}")
        self.credit = credit
        self.sent = 0        # frames shipped
        self.received = 0    # grant units absorbed

    def consume(self) -> None:
        if self.credit <= 0:
            raise WireError("sent frame without credit")
        self.credit -= 1
        self.sent += 1

    def feed_grant(self, status: int, payload: bytes) -> None:
        if status != STATUS_CREDIT:
            raise WireError(f"expected credit grant, got status {status}")
        n = decode_frame_payload(payload)
        if not (isinstance(n, int) and not isinstance(n, bool)
                and 1 <= n <= MAX_STREAM_CREDIT):
            raise WireError(f"bad credit grant {n!r}")
        self.credit += n
        self.received += n

    @property
    def outstanding(self) -> int:
        """Grant units still in flight from a well-behaved peer that
        grants one unit per consumed frame: after a clean stream end the
        server must absorb exactly this many before the connection is
        back in request/response framing."""
        return self.sent - self.received
