"""Streaming coprocessor: bounded-memory framed partial responses.

Reference: the CmdCopStream mode of /root/reference/store/tikv/
coprocessor.go:547-555 (handleCopStreamResult: incremental per-range
responses, stream re-created from the last returned range on region
errors) and mocktikv/cop_handler_dag.go's chunked DAG execution. The
materialized path (store/copr.py cop_handler) returns one response list
per region — a large region costs unbounded memory on both sides. This
module is the storage half of the streaming path:

  * `region_stream` executes the pushed-down scan/selection/partial-agg
    PER FRAME: raw KV rows accumulate until the response-size cap
    (tidb_tpu_copr_stream_frame_bytes), then decode + execute + yield one
    `StreamFrame`. An aggregating subplan yields per-frame PARTIAL
    aggregates the client merges incrementally (the "partial partial
    aggregates" shape — see PAPERS.md).
  * Every frame carries the contiguous key range it covers; frame i+1
    starts exactly where frame i ended, so a consumer that acked frame i
    can resume a dead stream at `frame.range.end` with no duplicate or
    missing row (store/copr.py `_run_task_stream`).
  * The final frame has `last=True` and `range.end` = the region-clamped
    scan end, telling the client where this region's coverage stops (the
    cursor for crossing into the next region).

Flow control lives one layer up: in-process consumption pulls the
generator lazily (perfect backpressure); the parallel fan-out buffers
frames in a `BoundedFrameQueue` sized to the credit window; the
out-of-process wire path uses the credit protocol of store/wire.py
(client grants N outstanding frames, the server blocks past the window
— store/remote.py).

Cache integration (the reason tidb_tpu_copr_stream can default on): a
stream over a cache-eligible range (no LIMIT, chunk cache enabled)
consults the SAME columnar cache hierarchy as the materialized handler
(store/copr.exec_cached_cop). A resident range serves as ONE final
frame straight from the decoded (and, for fused agg plans, the
HBM-device-resident) block — resume-safe, since nothing is acked until
that frame lands and a re-issue re-reads the same block. A COLD stream
keeps the bounded frame-by-frame contract for the client, and
additionally captures its decoded batches to fill the chunk cache at
stream end, so the next read — streamed or materialized — is hot.
Over-budget accumulations abort the fill: scans too large for a cache
entry stream exactly as before.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass, field

from tidb_tpu import config, metrics
from tidb_tpu.kv import CopRequest, KVRange
from tidb_tpu.util import failpoint

__all__ = ["StreamFrame", "region_stream", "cop_stream_handler",
           "BoundedFrameQueue", "stream_stats", "reset_stream_stats"]

# rows per engine-scan call while filling a frame; small enough that a
# frame overshoots its byte cap by at most one row, large enough to
# amortize the engine's lock
SCAN_SUB_BATCH = 1024


@dataclass
class StreamFrame:
    """One framed partial response (wire struct id 25, store/wire.py).

    `chunk` is the pushed subplan's result over exactly the raw rows in
    `range` (None when the frame only advances coverage); `range` is the
    contiguous scanned span — the resume boundary, NOT the result rows'
    keys (a filter may have dropped every row in it)."""

    chunk: object | None
    range: KVRange
    last: bool = False


# -- observability -----------------------------------------------------------

_stats_lock = threading.Lock()


def _fresh_stats() -> dict:
    return {"streams": 0, "frames": 0, "bytes": 0, "frame_bytes_max": 0,
            "credit_stalls": 0, "resumes": 0, "peak_buffered": 0}


_STATS = _fresh_stats()         # guarded-by: _stats_lock


def reset_stream_stats() -> None:
    with _stats_lock:
        _STATS.clear()
        _STATS.update(_fresh_stats())


def stream_stats() -> dict:
    with _stats_lock:
        return dict(_STATS)


def _note(key: str, inc: int = 1) -> None:
    with _stats_lock:
        _STATS[key] += inc


def _note_max(key: str, value: int) -> None:
    with _stats_lock:
        if value > _STATS[key]:
            _STATS[key] = value


def note_resume() -> None:
    """A client re-issued a stream from its last acked boundary."""
    _note("resumes")
    metrics.counter(metrics.COP_STREAM_RESUMES)


def note_credit_stall() -> None:
    """A producer blocked on an exhausted credit window (backpressure
    engaged — the bound worked, this is not an error)."""
    _note("credit_stalls")
    metrics.counter(metrics.COP_STREAM_CREDIT_STALLS)


# -- storage side ------------------------------------------------------------

# Over-cap memo: result sizes of cached frames _cached_frame REFUSED
# (result > client frame cap). The refusal itself costs a full fused
# dispatch whose result is thrown away — remembering the size lets the
# next warm stream over the same (cache key, data version) skip
# straight to the framed raw scan. The data version in the key
# invalidates naturally on write/DDL; stale tuples age out by LRU.
_OVERCAP_CAP = 256
_overcap_lock = threading.Lock()
# (cache key, dv) -> result bytes
_overcap: OrderedDict = OrderedDict()   # guarded-by: _overcap_lock


def _overcap_get(key, dv) -> int | None:
    with _overcap_lock:
        n = _overcap.get((key, dv))
        if n is not None:
            _overcap.move_to_end((key, dv))
        return n


def _overcap_put(key, dv, nbytes: int) -> None:
    with _overcap_lock:
        _overcap[(key, dv)] = nbytes
        _overcap.move_to_end((key, dv))
        while len(_overcap) > _OVERCAP_CAP:
            _overcap.popitem(last=False)


def _cached_frame(storage, region, req: CopRequest, plan, s: bytes,
                  e: bytes, frame_bytes: int, key, dv) -> \
        StreamFrame | None:
    """Serve one region's stream from the columnar cache hierarchy: the
    shared cached-path executor (filter memo, fused HBM agg dispatch)
    runs once and its response ships as ONE final frame covering the
    whole clamped range. Returns None — the caller streams framed from
    the raw scan instead — when the RESULT would bust the client's
    frame cap: agg partials are usually tiny, but a high-cardinality
    GROUP BY partial approaches the block size, and shipping it as one
    unbounded frame would break the streamed constant-client-memory
    contract. Resume-safe: a consumer that dies mid-frame acked
    nothing, and the re-issued stream re-reads the same resident
    block."""
    from tidb_tpu import memtrack
    from tidb_tpu.store.copr import exec_cached_cop

    responses = exec_cached_cop(storage, region, plan, s, e, req)
    chunk = responses[0].chunk if responses else None
    # agg partials ship as GroupResult, not Chunk — result_bytes sizes
    # both, so a high-cardinality partial cannot dodge the cap check
    nbytes = memtrack.result_bytes(chunk) if chunk is not None else 0
    if nbytes > frame_bytes:
        _overcap_put(key, dv, nbytes)
        return None
    _note("frames")
    _note("bytes", nbytes)
    _note_max("frame_bytes_max", nbytes)
    metrics.counter(metrics.COP_STREAM_FRAMES)
    metrics.counter(metrics.COP_STREAM_BYTES, inc=nbytes)
    return StreamFrame(chunk, KVRange(s, e), last=True)


def region_stream(storage, region, req: CopRequest, frame_bytes: int):
    """Yield StreamFrames for one region's share of `req`.

    Raw (key, value) rows accumulate until the next row would push the
    frame past `frame_bytes`; the pushed subplan then runs over exactly
    that batch. A single row larger than the cap still ships alone — the
    cap bounds buffering, it cannot split a row. Cache-eligible ranges
    consult and fill the columnar caches (module docstring)."""
    from tidb_tpu.store.copr import (clamp_range, decode_cop_batch,
                                     exec_cop_plan, use_cached_path)

    plan = req.plan
    # ONE clamp shared with the materialized handler: cache keys embed
    # (s, e), so both surfaces must clamp identically to share entries
    s, e = clamp_range(region, req.ranges[0])
    _note("streams")

    fill_key = fill_dv = None
    fill_parts: list | None = None
    fill_handles: list | None = None
    fill_bytes = fill_billed = 0
    resident = None
    if use_cached_path(storage, plan):
        from tidb_tpu.store.chunk_cache import ChunkCache
        cache = storage.chunk_cache
        key = ChunkCache.key(region, plan, s, e)
        dv = storage.engine.data_version
        resident = cache.peek(key, dv, req.start_ts)
        known = _overcap_get(key, dv)
        if resident is not None and (plan.is_agg or
                                     resident <= frame_bytes) and \
                (known is None or known <= frame_bytes):
            # hot range whose response respects the client's frame cap
            # (agg partials are usually tiny; a raw block only
            # qualifies when it fits one frame): serve straight from
            # residency. peek, so the real lookup inside
            # exec_cached_cop does the hit counting exactly once. A
            # bigger raw block — or an agg partial that turns out to
            # bust the cap (None below, size memoized so the next warm
            # stream skips the wasted dispatch) — streams framed from
            # the raw scan instead: one frame per range is the resume
            # unit, so a resident block can never be split across
            # frames.
            frame = _cached_frame(storage, region, req, plan, s, e,
                                  frame_bytes, key, dv)
            if frame is not None:
                yield frame
                return
        # cold: stream frames exactly as before (the client's memory
        # bound), capturing decoded batches for an end-of-stream fill
        # under the same MVCC conditions as the materialized filler
        # (store/copr._cached_range_chunk). Already-resident ranges
        # (over-cap raw blocks) skip the re-capture.
        if resident is None and not storage.engine._locked_keys and \
                req.start_ts >= storage.engine.max_commit_ts:
            fill_key, fill_dv, fill_parts = key, dv, []
            from tidb_tpu.store.copr import _delta_store_of
            if _delta_store_of(storage) is not None and \
                    plan.index is None:
                # capture row handles alongside: stream-filled entries
                # then patch forward as base⋈delta (store/delta.py)
                # exactly like materialized fills
                fill_handles = []

    remaining = plan.limit if not plan.is_agg else None
    pend: list[tuple[bytes, bytes]] = []
    pend_bytes = 0
    frame_start = s
    cur = s
    done = False

    def emit(boundary: bytes, last: bool) -> StreamFrame:
        nonlocal pend, pend_bytes, frame_start, remaining, \
            fill_parts, fill_handles, fill_bytes, fill_billed
        # injectable frame fault BEFORE the frame materializes: an
        # un-emitted frame was never acked, so the client resume from
        # its last acked range boundary loses no rows (fires on both
        # the in-process shim path and the remote transport)
        failpoint.eval("copr/stream-frame", region.id)
        chunk = None
        if pend:
            dec = decode_cop_batch(plan, pend)
            if fill_handles is not None and fill_parts is not None:
                from tidb_tpu.store.delta import record_handles
                fill_handles.append(record_handles(
                    [k for k, _v in pend]))
            if fill_parts is not None:
                from tidb_tpu import memtrack
                part = memtrack.chunk_bytes(dec)
                # the capture is real statement memory until it is
                # handed to the cache: bill it, so quotas see a cold
                # cacheable stream exactly like the materialized read
                # path's whole-range buffering (a QuotaExceeded raised
                # here cancels the statement before the buffer grows).
                # fill_billed grows BEFORE consume: the charge lands on
                # the ledgers before the quota check raises, so the
                # finally below must release it too
                fill_billed += part
                memtrack.consume(plan, host=part)
                fill_parts.append(dec)
                fill_bytes += part
                if fill_bytes > storage.chunk_cache.max_bytes:
                    # outgrew the cache: this scan is exactly what
                    # streaming exists for — abort the fill (and give
                    # the dropped buffer back to the ledger now)
                    fill_parts = None
                    memtrack.release(plan, host=fill_billed)
                    fill_billed = 0
            resp = exec_cop_plan(plan, dec)
            chunk = resp.chunk
            if remaining is not None:
                remaining -= chunk.num_rows
        frame = StreamFrame(chunk, KVRange(frame_start, boundary), last)
        nbytes = pend_bytes
        pend, pend_bytes, frame_start = [], 0, boundary
        _note("frames")
        _note("bytes", nbytes)
        _note_max("frame_bytes_max", nbytes)
        metrics.counter(metrics.COP_STREAM_FRAMES)
        metrics.counter(metrics.COP_STREAM_BYTES, inc=nbytes)
        return frame

    try:
        while not done:
            batch = storage.engine.scan(cur, e, SCAN_SUB_BATCH,
                                        req.start_ts, req.isolation,
                                        desc=False)
            if not batch:
                break
            for k, v in batch:
                row_bytes = len(k) + len(v) + 16   # ~ per-row overhead
                if pend and pend_bytes + row_bytes > frame_bytes:
                    yield emit(k, last=False)
                    if remaining is not None and remaining <= 0:
                        done = True
                        break
                pend.append((k, v))
                pend_bytes += row_bytes
            cur = batch[-1][0] + b"\x00"
            if not done and remaining is not None and pend:
                # a pushed-down LIMIT stops per scan sub-batch, like the
                # materialized handler — never buffer a whole byte-cap
                # frame of rows a LIMIT 7 will throw away
                yield emit(cur, last=False)
                if remaining <= 0:
                    done = True
            if len(batch) < SCAN_SUB_BATCH:
                break        # range exhausted: skip the empty re-probe
        yield emit(e, last=True)
        if fill_parts is not None:
            # the whole range streamed under fill-eligible conditions:
            # the next reader (streamed or materialized) is hot. An
            # abandoned generator never reaches here — no partial-range
            # fills.
            from tidb_tpu.chunk import Chunk
            from tidb_tpu.store.copr import decode_cop_batch as _dec
            whole = Chunk.concat_all(fill_parts) if fill_parts else None
            if whole is None:
                whole = _dec(plan, [])
            if fill_handles is not None:
                import numpy as _np
                whole._scan_handles = _np.concatenate(fill_handles) \
                    if fill_handles else _np.zeros(0, dtype=_np.int64)
            storage.chunk_cache.put(fill_key, fill_dv, req.start_ts,
                                    whole)
    finally:
        # capture handed to the cache (or dropped, or the generator
        # abandoned/cancelled mid-stream): it is no longer statement
        # memory either way
        if fill_billed:
            from tidb_tpu import memtrack
            memtrack.release(plan, host=fill_billed)


def cop_stream_handler(storage):
    """Handler closure installed into the RPC shim (the streaming
    counterpart of store/copr.cop_handler): (region, req) -> generator
    of StreamFrames. The frame cap comes FROM THE CLIENT with each
    request (the session's sysvar — out of process, the server's own
    config must not override the client's memory bound); the server
    sysvar is only the fallback for callers that don't send one."""

    def handle(region, req: CopRequest, frame_bytes=None):
        return region_stream(storage, region, req,
                             frame_bytes or
                             config.copr_stream_frame_bytes())

    return handle


# -- client-side bounded buffering -------------------------------------------

class BoundedFrameQueue:
    """Credit-window buffer between producer threads and one consumer:
    the in-process analogue of the wire protocol's credit flow control.
    Capacity = credit window; a put past it blocks (counted as a credit
    stall — the producer is being backpressured, not buffered)."""

    _DONE = object()

    def __init__(self, credit: int, stop: threading.Event):
        import queue
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, credit))
        self._stop = stop
        self._queue_mod = queue

    def put(self, item) -> bool:
        """-> False when the consumer has gone away (stop producing)."""
        stalled = False
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.05)
                _note_max("peak_buffered", self._q.qsize())
                return True
            except self._queue_mod.Full:
                if not stalled:
                    stalled = True
                    note_credit_stall()
        return False

    def put_done(self) -> None:
        # sentinel bypasses the stall accounting but not the bound
        while not self._stop.is_set():
            try:
                self._q.put(self._DONE, timeout=0.05)
                return
            except self._queue_mod.Full:
                pass

    def drain(self, producers: int):
        """Yield items until `producers` DONE sentinels arrived.
        Exceptions put by producers re-raise in the consumer."""
        finished = 0
        while finished < producers:
            item = self._q.get()
            if item is self._DONE:
                finished += 1
            elif isinstance(item, BaseException):
                raise item
            else:
                yield item
