"""Streaming coprocessor: bounded-memory framed partial responses.

Reference: the CmdCopStream mode of /root/reference/store/tikv/
coprocessor.go:547-555 (handleCopStreamResult: incremental per-range
responses, stream re-created from the last returned range on region
errors) and mocktikv/cop_handler_dag.go's chunked DAG execution. The
materialized path (store/copr.py cop_handler) returns one response list
per region — a large region costs unbounded memory on both sides. This
module is the storage half of the streaming path:

  * `region_stream` executes the pushed-down scan/selection/partial-agg
    PER FRAME: raw KV rows accumulate until the response-size cap
    (tidb_tpu_copr_stream_frame_bytes), then decode + execute + yield one
    `StreamFrame`. An aggregating subplan yields per-frame PARTIAL
    aggregates the client merges incrementally (the "partial partial
    aggregates" shape — see PAPERS.md).
  * Every frame carries the contiguous key range it covers; frame i+1
    starts exactly where frame i ended, so a consumer that acked frame i
    can resume a dead stream at `frame.range.end` with no duplicate or
    missing row (store/copr.py `_run_task_stream`).
  * The final frame has `last=True` and `range.end` = the region-clamped
    scan end, telling the client where this region's coverage stops (the
    cursor for crossing into the next region).

Flow control lives one layer up: in-process consumption pulls the
generator lazily (perfect backpressure); the parallel fan-out buffers
frames in a `BoundedFrameQueue` sized to the credit window; the
out-of-process wire path uses the credit protocol of store/wire.py
(client grants N outstanding frames, the server blocks past the window
— store/remote.py). The chunk cache (store/chunk_cache.py) is bypassed:
streaming exists precisely for scans too large to sit in a cache entry.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

from tidb_tpu import config, metrics
from tidb_tpu.kv import CopRequest, KVRange

__all__ = ["StreamFrame", "region_stream", "cop_stream_handler",
           "BoundedFrameQueue", "stream_stats", "reset_stream_stats"]

# rows per engine-scan call while filling a frame; small enough that a
# frame overshoots its byte cap by at most one row, large enough to
# amortize the engine's lock
SCAN_SUB_BATCH = 1024


@dataclass
class StreamFrame:
    """One framed partial response (wire struct id 25, store/wire.py).

    `chunk` is the pushed subplan's result over exactly the raw rows in
    `range` (None when the frame only advances coverage); `range` is the
    contiguous scanned span — the resume boundary, NOT the result rows'
    keys (a filter may have dropped every row in it)."""

    chunk: object | None
    range: KVRange
    last: bool = False


# -- observability -----------------------------------------------------------

_stats_lock = threading.Lock()


def _fresh_stats() -> dict:
    return {"streams": 0, "frames": 0, "bytes": 0, "frame_bytes_max": 0,
            "credit_stalls": 0, "resumes": 0, "peak_buffered": 0}


_STATS = _fresh_stats()


def reset_stream_stats() -> None:
    with _stats_lock:
        _STATS.clear()
        _STATS.update(_fresh_stats())


def stream_stats() -> dict:
    with _stats_lock:
        return dict(_STATS)


def _note(key: str, inc: int = 1) -> None:
    with _stats_lock:
        _STATS[key] += inc


def _note_max(key: str, value: int) -> None:
    with _stats_lock:
        if value > _STATS[key]:
            _STATS[key] = value


def note_resume() -> None:
    """A client re-issued a stream from its last acked boundary."""
    _note("resumes")
    metrics.counter(metrics.COP_STREAM_RESUMES)


def note_credit_stall() -> None:
    """A producer blocked on an exhausted credit window (backpressure
    engaged — the bound worked, this is not an error)."""
    _note("credit_stalls")
    metrics.counter(metrics.COP_STREAM_CREDIT_STALLS)


# -- storage side ------------------------------------------------------------

def region_stream(storage, region, req: CopRequest, frame_bytes: int):
    """Yield StreamFrames for one region's share of `req`.

    Raw (key, value) rows accumulate until the next row would push the
    frame past `frame_bytes`; the pushed subplan then runs over exactly
    that batch. A single row larger than the cap still ships alone — the
    cap bounds buffering, it cannot split a row."""
    from tidb_tpu.store.copr import decode_cop_batch, exec_cop_plan

    plan = req.plan
    rng: KVRange = req.ranges[0]
    s = max(rng.start, region.start)
    if region.end and rng.end:
        e = min(rng.end, region.end)
    else:
        e = region.end or rng.end   # either bound may be open (falsy)
    _note("streams")

    remaining = plan.limit if not plan.is_agg else None
    pend: list[tuple[bytes, bytes]] = []
    pend_bytes = 0
    frame_start = s
    cur = s
    done = False

    def emit(boundary: bytes, last: bool) -> StreamFrame:
        nonlocal pend, pend_bytes, frame_start, remaining
        chunk = None
        if pend:
            resp = exec_cop_plan(plan, decode_cop_batch(plan, pend))
            chunk = resp.chunk
            if remaining is not None:
                remaining -= chunk.num_rows
        frame = StreamFrame(chunk, KVRange(frame_start, boundary), last)
        nbytes = pend_bytes
        pend, pend_bytes, frame_start = [], 0, boundary
        _note("frames")
        _note("bytes", nbytes)
        _note_max("frame_bytes_max", nbytes)
        metrics.counter(metrics.COP_STREAM_FRAMES)
        metrics.counter(metrics.COP_STREAM_BYTES, inc=nbytes)
        return frame

    while not done:
        batch = storage.engine.scan(cur, e, SCAN_SUB_BATCH, req.start_ts,
                                    req.isolation, desc=False)
        if not batch:
            break
        for k, v in batch:
            row_bytes = len(k) + len(v) + 16   # 16 ~ per-row list overhead
            if pend and pend_bytes + row_bytes > frame_bytes:
                yield emit(k, last=False)
                if remaining is not None and remaining <= 0:
                    done = True
                    break
            pend.append((k, v))
            pend_bytes += row_bytes
        cur = batch[-1][0] + b"\x00"
        if not done and remaining is not None and pend:
            # a pushed-down LIMIT stops per scan sub-batch, like the
            # materialized handler — never buffer a whole byte-cap frame
            # of rows a LIMIT 7 will throw away
            yield emit(cur, last=False)
            if remaining <= 0:
                done = True
        if len(batch) < SCAN_SUB_BATCH:
            break
    yield emit(e, last=True)


def cop_stream_handler(storage):
    """Handler closure installed into the RPC shim (the streaming
    counterpart of store/copr.cop_handler): (region, req) -> generator
    of StreamFrames. The frame cap comes FROM THE CLIENT with each
    request (the session's sysvar — out of process, the server's own
    config must not override the client's memory bound); the server
    sysvar is only the fallback for callers that don't send one."""

    def handle(region, req: CopRequest, frame_bytes=None):
        return region_stream(storage, region, req,
                             frame_bytes or
                             config.copr_stream_frame_bytes())

    return handle


# -- client-side bounded buffering -------------------------------------------

class BoundedFrameQueue:
    """Credit-window buffer between producer threads and one consumer:
    the in-process analogue of the wire protocol's credit flow control.
    Capacity = credit window; a put past it blocks (counted as a credit
    stall — the producer is being backpressured, not buffered)."""

    _DONE = object()

    def __init__(self, credit: int, stop: threading.Event):
        import queue
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, credit))
        self._stop = stop
        self._queue_mod = queue

    def put(self, item) -> bool:
        """-> False when the consumer has gone away (stop producing)."""
        stalled = False
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.05)
                _note_max("peak_buffered", self._q.qsize())
                return True
            except self._queue_mod.Full:
                if not stalled:
                    stalled = True
                    note_credit_stall()
        return False

    def put_done(self) -> None:
        # sentinel bypasses the stall accounting but not the bound
        while not self._stop.is_set():
            try:
                self._q.put(self._DONE, timeout=0.05)
                return
            except self._queue_mod.Full:
                pass

    def drain(self, producers: int):
        """Yield items until `producers` DONE sentinels arrived.
        Exceptions put by producers re-raise in the consumer."""
        finished = 0
        while finished < producers:
            item = self._q.get()
            if item is self._DONE:
                finished += 1
            elif isinstance(item, BaseException):
                raise item
            else:
                yield item
