"""HBM-resident columnar region-block cache: hot columns live where the
compute is.

The host-side chunk cache (store/chunk_cache.py) kills the KV-scan +
decode cost of repeated analytical reads, but every execution still
re-paid the host->device transfer unless the SAME chunk object happened
to carry a device memo — an invisible, per-object, unbudgeted residency
that evaporates with the host entry and never helps the streaming path.
BENCH r05 put the device scan path at ~0.23 of the memory roofline
largely on that re-upload. This module is the TiFlash-columnar-replica
analogue one level further down (PAPER.md): the storage node keeps the
PADDED, DICT-ENCODED device arrays per region block resident in HBM,
keyed by (region, schema fingerprint, range) and validated by the
engine's data version, so a repeated TPC-H scan reads straight from HBM
and the fused scan->filter->partial-agg dispatch (store/copr.py) starts
from device-resident columns.

MVCC correctness is inherited from the chunk cache's contract — the
(fill_version, fill_ts, delta_watermark) freshness triple
(store/chunk_cache.py module docstring): an entry records the engine's
STRUCTURAL data_version and the fill snapshot ts, and is served only
when the version is unchanged AND read_ts >= fill_ts. Structural
changes (DDL/meta mutations, GC, delete-range, bulk import) still bump
the version and invalidate on the next lookup; committed ROW mutations
are journaled by the delta store instead (store/delta.py) and FOLDED
INTO the resident block in place — get() applies the journal window
(fill_ts, read_ts] as device-side scatters (updates overwrite,
deletes swap-remove, inserts fill the padding tail, dict columns
extend incrementally) and advances fill_ts to the delta watermark, so
an OLTP write stream no longer re-colds the HBM plane. Pending locks
are handled by the engine's serve-time locked_in_range veto before the
cache is consulted. Fills are allowed exactly where chunk-cache fills
are (no pending locks, snapshot covers every commit), and the caller
passes the HOST entry's effective fill_ts (the delta watermark when
serving base⋈delta) so both caches agree on validity.

Budget: `tidb_tpu_device_cache_bytes` bounds resident bytes with LRU
eviction (re-read on every lookup AND fill, so SET takes effect on the
next access). Residency is charged to a dedicated memtrack node under
the SERVER root (device ledger), so information_schema.memory_usage and
the server gauges see the cache like any other consumer, and `shed()`
is registered on SERVER's spill-action chain so one call reclaims every
live cache. That chain is ARMED (ROADMAP item 1 delivered): the
admission controller (tidb_tpu/sched.py) drives it when a statement's
projected footprint would push the server past
`tidb_tpu_server_mem_quota` — resident cache blocks are the first thing
shed to make room — and the status port's /shed endpoint fires the same
chain on operator demand.
"""

from __future__ import annotations

import threading
import weakref
from collections import OrderedDict

import numpy as np

from tidb_tpu import config, memtrack, metrics, trace
from tidb_tpu.util import failpoint

__all__ = ["DeviceBlock", "DeviceCache", "upload_block", "tracker",
           "block_replicas", "shed_all"]


_tracker_lock = threading.Lock()
_tracker: memtrack.MemTracker | None = None   # guarded-by: _tracker_lock

# every live cache, for the single server-wide OOM shed action; weak so
# short-lived test storages don't accumulate forever
_caches: "weakref.WeakSet[DeviceCache]" = \
    weakref.WeakSet()               # guarded-by: _tracker_lock
_shed_registered = False            # guarded-by: _tracker_lock


def tracker() -> memtrack.MemTracker:
    """The shared server-scope tracker node all device caches charge
    (label `hbm-cache`, device ledger)."""
    global _tracker
    with _tracker_lock:
        if _tracker is None:
            _tracker = memtrack.server_node("hbm-cache")
        return _tracker


def _shed_all() -> None:
    """The registered memtrack OOM action: drop every resident block in
    every live cache, returning the hbm-cache ledger to zero. The
    WeakSet is snapshotted under its lock — iterating it bare races a
    concurrent cache construction's add() and raises RuntimeError,
    which the spill chain would silently swallow."""
    with _tracker_lock:
        caches = list(_caches)
    for cache in caches:
        cache.shed()


def shed_all() -> None:
    """Invalidate every resident block in every live cache — the
    memtrack OOM action, and the device-quarantine path
    (sched.DeviceHealth): blocks uploaded through a faulting device
    plane are not trustworthy, and nothing can consume them while the
    device is quarantined anyway."""
    _shed_all()


def _release_resident(resident: list) -> None:
    """GC finalizer: credit back whatever a dead cache still held."""
    freed, resident[0] = resident[0], 0
    if freed:
        tracker().release(device=freed)


def _register(cache: "DeviceCache") -> None:
    global _shed_registered
    with _tracker_lock:
        _caches.add(cache)
        if not _shed_registered:
            memtrack.SERVER.add_spill_action(_shed_all)
            _shed_registered = True


def upload_block(chunk, size: int | None = None):
    """The ONE audited upload site for region columns (lint rule
    `device-cache`): pad + dict-encode + device_put without the
    per-chunk memo (the cache owns residency; a second resident copy
    memoized on the chunk would double HBM). -> (cols, dicts).

    On a multi-chip ``("batch",)`` plane blocks upload REPLICATED
    (``NamedSharding(mesh, P())``): a point lookup then runs on
    whichever chip the scheduler grant places it, no cross-chip fetch.
    The N× HBM cost is billed honestly by fill() (nbytes × replicas),
    so the budget/eviction math sees the real footprint."""
    import jax

    from tidb_tpu import devplane
    from tidb_tpu.ops import runtime
    if devplane.ndev() <= 1:
        return runtime.device_put_chunk(chunk, size, memo=False)
    cols, dicts = runtime.device_put_chunk(chunk, size,
                                           to_device=False, memo=False)
    cols = jax.device_put(cols, devplane.replicated())
    return cols, dicts


def block_replicas() -> int:
    """Replication factor of a block uploaded NOW (the plane's device
    count): fill() bills nbytes × this so the hbm-cache ledger carries
    the true multi-chip footprint."""
    from tidb_tpu import devplane
    return devplane.ndev()


class DeviceBlock:
    """One resident region block: the padded device columns exactly as a
    kernel dispatch consumes them, plus the host dictionaries needed to
    decode varlen lanes.

    Blocks are IMMUTABLE once handed out: the delta patch path
    (apply-pending, store/delta.py) builds a NEW block from scatter
    updates over this one's device arrays and swaps the cache entry, so
    a reader that captured this block mid-dispatch keeps a consistent
    (cols, nrows) pair. `handles`/`pos_handles`/`hmap` are the
    host-side row-position index that makes the device patch possible;
    they hand off to the successor block (only the entry's current
    block is ever patched)."""

    __slots__ = ("cols", "dicts", "nrows", "size", "nbytes",
                 "handles", "pos_handles", "hmap", "dictmaps")

    def __init__(self, cols, dicts, nrows: int, size: int, nbytes: int,
                 handles=None):
        self.cols = cols
        self.dicts = dicts
        self.nrows = nrows
        self.size = size
        self.nbytes = nbytes
        self.handles = handles      # np int64 [nrows] or None
        self.pos_handles = None     # np int64 [size], built lazily
        self.hmap = None            # handle -> row position
        self.dictmaps = None        # col idx -> value -> code


class DeviceCache:
    """LRU over device-resident region blocks, bounded by the
    `tidb_tpu_device_cache_bytes` budget (read per operation, so SET
    takes effect immediately), accounted on the shared hbm-cache
    memtrack node."""

    def __init__(self):
        self._mu = threading.Lock()
        self._entries: OrderedDict = OrderedDict()   # guarded-by: _mu
        # resident bytes live in a one-slot list shared with a GC
        # finalizer: a cache dropped without close() (test storages,
        # abandoned servers) still returns its ledger share, so the
        # hbm-cache node stays exact over the process lifetime
        self._resident = [0]        # guarded-by: _mu
        # bytes dropped under the lock, not settled
        self._pending = 0           # guarded-by: _mu
        weakref.finalize(self, _release_resident, self._resident)
        _register(self)

    @staticmethod
    def key(region, plan, s: bytes, e: bytes):
        """(region, schema fingerprint, range): region id+version, table/
        index ids, the column ids AND their field-type codes (a DDL that
        re-types a column without re-numbering it must not alias), the
        handle flag, and the clamped scan range."""
        from tidb_tpu.store.chunk_cache import ChunkCache
        return (ChunkCache.key(region, plan, s, e),
                tuple(getattr(c.ft, "tp", None) for c in plan.cols))

    def enabled(self) -> bool:
        """Consulted on every agg request. A budget of 0 not only stops
        lookups, it RECLAIMS: resident blocks shed on the next consult,
        so `SET tidb_tpu_device_cache_bytes = 0` actually frees the HBM
        it promises to (the shrink-on-lookup path in get() is
        unreachable once this gate stops all lookups). A transient
        `tidb_tpu_device = 0` keeps residency — flipping the device off
        and on must not cold-start the cache."""
        if config.device_cache_bytes() <= 0:
            if self._resident[0]:
                self.shed()
            return False
        return config.device_enabled()

    def resident_bytes(self) -> int:
        with self._mu:
            return self._resident[0]

    def __len__(self) -> int:
        with self._mu:
            return len(self._entries)

    # -- lookup / fill -------------------------------------------------------

    def get(self, key, data_version: int, read_ts: int,
            pend_fn=None) -> DeviceBlock | None:
        """Resident block for `key`, valid for a reader at `read_ts`
        under the current engine `data_version`; a version/ts mismatch
        drops the stale entry (counted as an eviction). The budget is
        re-read here too, so a shrunk `tidb_tpu_device_cache_bytes`
        takes effect on the next lookup — not only at the next fill —
        evicting LRU entries (the served block last) until residency
        fits.

        `pend_fn(lo_ts, hi_ts)` — supplied by the coprocessor serve
        path (store/copr.py) — returns the table's staged delta for
        this block's range in (lo_ts, hi_ts] (store/delta.py): a
        PendingDelta with its plan-layout decode, delta.STALE when the
        journal was truncated under the entry, or None. A pending delta
        is folded INTO the resident block in place — value/validity
        scatters plus tail appends into the padding, dict columns
        extended incrementally — and the entry's fill_ts advances to
        the watermark, so the HBM plane stays hot across OLTP writes
        instead of re-uploading the whole block."""
        budget = config.device_cache_bytes()
        for _ in range(4):      # bounded retry under patch races
            with self._mu:
                ent = self._entries.get(key)
                if ent is None:
                    metrics.counter(metrics.HBM_CACHE_MISSES)
                    return None
                fill_version, fill_ts, block = ent
                if fill_version != data_version:
                    # stale for EVERY reader: drop now, not at LRU
                    # pressure
                    self._drop_locked(key)
                    metrics.counter(metrics.HBM_CACHE_MISSES)
                    metrics.counter(metrics.HBM_CACHE_EVICTIONS)
                    stale = True
                elif read_ts < fill_ts:
                    # too old for THIS reader only — newer snapshots
                    # still serve from it, so the entry stays
                    metrics.counter(metrics.HBM_CACHE_MISSES)
                    return None
                else:
                    stale = False
            if stale:
                self._settle()
                return None
            # the delta query + plan-layout decode run with _mu
            # dropped; the patch below re-validates the entry under it
            pend = pend_fn(fill_ts, read_ts) if pend_fn is not None \
                else None
            if pend is None:
                with self._mu:
                    if self._entries.get(key) is not None:
                        self._entries.move_to_end(key)
                    while self._resident[0] > budget and self._entries:
                        self._drop_locked(next(iter(self._entries)))
                        metrics.counter(metrics.HBM_CACHE_EVICTIONS)
                    # the served block stays alive through the returned
                    # reference even if it was the one over budget; it
                    # is simply no longer resident for the next reader
                    metrics.counter(metrics.HBM_CACHE_HITS)
                self._settle()
                return block
            if getattr(pend, "watermark", None) is None:
                # delta.STALE sentinel: journal truncated under the
                # entry — it cannot be patched forward any more
                self.drop(key, if_block=block)
                metrics.counter(metrics.HBM_CACHE_MISSES)
                self._settle()
                return None
            with self._mu:
                ent2 = self._entries.get(key)
                if ent2 is None or ent2[2] is not block or \
                        ent2[1] != fill_ts:
                    continue    # raced with another patch: re-evaluate
                with trace.span("hbm.patch",
                                rows=len(pend.upsert_handles)):
                    patched = self._patch_locked(key, ent2, pend)
            if patched is not None:
                metrics.counter(metrics.HBM_CACHE_HITS)
                self._settle()
                # THIS thread's patched block — at exactly pend's
                # watermark — never the entry's current one: a newer
                # reader may already have patched past this reader's
                # read_ts, and handing that block back here would leak
                # later commits into an older snapshot
                return patched
            # unpatchable (no handles, dtype drift, tail overflow):
            # drop; the caller re-fills from the merged host chunk
            self.drop(key, if_block=block)
            metrics.counter(metrics.HBM_CACHE_MISSES)
            self._settle()
            return None
        metrics.counter(metrics.HBM_CACHE_MISSES)
        return None

    def fill(self, key, data_version: int, fill_ts: int,
             chunk) -> DeviceBlock | None:
        """Upload `chunk`'s padded columns and insert. Returns None (no
        upload) when the block alone would exceed the budget. The caller
        owns the MVCC fill contract (see module docstring)."""
        from tidb_tpu.ops.runtime import bucket_size
        # injectable upload fault: a raise here (chaos arms
        # DeviceFaultError) is a device-plane fault the dispatch
        # site's retry/degrade/quarantine chain absorbs
        failpoint.eval("hbm/fill")
        budget = config.device_cache_bytes()
        size = bucket_size(max(chunk.num_rows, 1))
        # a multi-chip plane replicates the block to every chip (any
        # chip serves it): the budget sees the full N× footprint
        nbytes = memtrack.device_put_bytes(chunk, size) * block_replicas()
        if nbytes > budget:
            return None
        with trace.span("hbm.fill", rows=chunk.num_rows, bytes=nbytes):
            cols, dicts = upload_block(chunk, size)
        block = DeviceBlock(cols, dicts, chunk.num_rows, size, nbytes,
                            handles=getattr(chunk, "_scan_handles",
                                            None))
        with self._mu:
            if key in self._entries:
                self._drop_locked(key)
            self._entries[key] = (data_version, fill_ts, block)
            self._resident[0] += nbytes
            while self._resident[0] > budget and len(self._entries) > 1:
                old = next(iter(self._entries))
                if old == key:      # never evict the entry just filled
                    break
                self._drop_locked(old)
                metrics.counter(metrics.HBM_CACHE_EVICTIONS)
        # lint: exempt[paired-resource] ownership transfer: residency releases on evict/shed; a GC finalizer backstops dead caches
        tracker().consume(device=nbytes)
        # evictions released under the lock tally in _pending_release;
        # settle them against the shared tracker outside the lock
        self._settle()
        return block

    def get_or_fill(self, key, data_version: int, read_ts: int, chunk,
                    fill_ts: int | None = None,
                    pend_fn=None) -> DeviceBlock | None:
        """get(); on miss, fill() when `fill_ts` is provided (the
        caller's signal that the MVCC fill conditions hold). `chunk` is
        the HOST-side truth for this reader — on the delta path the
        base⋈delta merge — so an unpatchable block re-fills from
        exactly the state the entry's new fill_ts describes."""
        hit = self.get(key, data_version, read_ts, pend_fn=pend_fn)
        if hit is not None:
            return hit
        if fill_ts is None:
            return None
        return self.fill(key, data_version, fill_ts, chunk)

    # -- the in-place delta patch (store/delta.py) ---------------------------

    def _patch_locked(self, key, ent, pend) -> "DeviceBlock | None":
        """Fold one PendingDelta into the entry's resident block:
        updates overwrite rows in place, deletes swap-remove (order is
        free — only agg plans consume resident blocks), inserts land in
        the padding tail (or freed holes), dict columns extend
        incrementally. Builds a NEW DeviceBlock over the scattered
        device arrays and swaps the entry, so concurrent readers keep a
        consistent (cols, nrows) snapshot. -> False when the block
        cannot be patched (no handles, layout drift, tail overflow);
        the caller then drops it and re-fills from the merged host
        chunk. Called under _mu; the scatters are async device
        dispatches, not syncs."""
        # injectable patch fault, fired BEFORE any state mutates (an
        # armed raise leaves the entry exactly as it was; _mu releases
        # on unwind). A returned sentinel simulates "unpatchable":
        # the caller drops the block and re-fills from the host chunk
        if failpoint.eval("hbm/patch") is not None:
            return None
        fill_version, _fill_ts, block = ent
        dchunk = pend.decoded
        if block.handles is None or dchunk is None or \
                dchunk.num_cols != len(block.cols):
            return None
        nrows, size = block.nrows, block.size
        if block.hmap is None:
            ph = np.full(size, -1, dtype=np.int64)
            ph[:nrows] = block.handles[:nrows]
            block.pos_handles = ph
            block.hmap = {int(h): i
                          for i, h in enumerate(block.handles[:nrows])}
        hmap, pos_handles = block.hmap, block.pos_handles
        upd_idx: list = []
        upd_src: list = []
        app_src: list = []
        dead: list = []
        for i, h in enumerate(pend.upsert_handles.tolist()):
            p = hmap.get(h)
            if p is not None:
                upd_idx.append(p)
                upd_src.append(i)
            else:
                app_src.append(i)
        for h in pend.delete_handles.tolist():
            p = hmap.get(h)
            if p is not None:
                dead.append(p)
        new_nrows = nrows - len(dead) + len(app_src)
        if new_nrows > size:
            return None             # padding exhausted: re-fill
        dead_set = set(dead)
        free = sorted(p for p in dead if p < new_nrows)
        if new_nrows > nrows:
            free.extend(range(nrows, new_nrows))
        # live rows stranded past the new row count move into leftover
        # holes (values gathered on device, no host round trip)
        movers = [p for p in range(new_nrows, nrows)
                  if p not in dead_set]
        app_dst = free[:len(app_src)]
        holes = free[len(app_src):]
        if len(holes) != len(movers):
            return None             # accounting drift: bail safely
        move_map = dict(zip(movers, holes))
        # pad index vectors to powers of two, repeating the last entry
        # (scatter-idempotent): the eager XLA scatters then compile for
        # log2 shapes instead of one program per delta batch size
        write_idx, write_rows = self._pad_pow2(
            np.asarray([move_map.get(p, p) for p in upd_idx] + app_dst,
                       dtype=np.int64),
            np.asarray(upd_src + app_src, dtype=np.int64))
        move_src, move_dst = self._pad_pow2(
            np.asarray(movers, dtype=np.int64),
            np.asarray(holes, dtype=np.int64))
        new_cols = []
        from tidb_tpu.chunk import dict_encode
        for j, (data, valid) in enumerate(block.cols):
            col = dchunk.columns[j]
            if j in block.dicts:
                codes, cvalid = self._encode_against(block, j, col)
            else:
                if col.data.dtype != np.dtype(data.dtype):
                    return None     # layout drift since the fill
                codes, cvalid = col.data, col.valid
            wvals = codes[write_rows] if len(write_rows) else \
                np.zeros(0, dtype=codes.dtype)
            wvalid = cvalid[write_rows] if len(write_rows) else \
                np.zeros(0, dtype=bool)
            if len(move_src):
                data = data.at[move_dst].set(data[move_src])
                valid = valid.at[move_dst].set(valid[move_src])
            if len(write_idx):
                data = data.at[write_idx].set(wvals)
                valid = valid.at[write_idx].set(wvalid)
            new_cols.append((data, valid))
        # host-side position index follows the same moves/writes
        for src, dst in move_map.items():
            h = int(pos_handles[src])
            pos_handles[dst] = h
            hmap[h] = dst
        for p, i in zip(write_idx.tolist(), write_rows.tolist()):
            h = int(pend.upsert_handles[i])
            pos_handles[p] = h
            hmap[h] = p
        for h in pend.delete_handles.tolist():
            hmap.pop(int(h), None)
        pos_handles[new_nrows:nrows] = -1
        nb = DeviceBlock(new_cols, block.dicts, new_nrows, size,
                         block.nbytes, handles=None)
        # the position index hands off: only the entry's CURRENT block
        # is ever patched, the predecessor keeps serving readers that
        # already hold it
        nb.pos_handles, nb.hmap = pos_handles, hmap
        nb.dictmaps = block.dictmaps
        nb.handles = nb.pos_handles[:new_nrows]
        block.hmap = block.pos_handles = None
        self._entries[key] = (fill_version, pend.watermark, nb)
        return nb

    @staticmethod
    def _pad_pow2(*arrs):
        """Pad parallel index vectors to the next power of two by
        repeating their last element — scatter-idempotent padding."""
        n = len(arrs[0])
        if n == 0:
            return arrs
        b = 1
        while b < n:
            b <<= 1
        if b == n:
            return arrs
        return tuple(np.concatenate([a, np.repeat(a[-1:], b - n)])
                     for a in arrs)

    @staticmethod
    def _encode_against(block: DeviceBlock, j: int, col):
        """Dict-encode a delta column against the block's existing
        dictionary, EXTENDING it for unseen values (new codes append;
        old codes — and every reader holding them — stay valid).
        Mirrors chunk.dict_encode's collation keying."""
        values = block.dicts[j]
        if block.dictmaps is None:
            block.dictmaps = {}
        dmap = block.dictmaps.get(j)
        ci = col.ft.is_ci
        if ci:
            from tidb_tpu.sqltypes import collation_key
        if dmap is None:
            if ci:
                dmap = {collation_key(v): c
                        for c, v in enumerate(values)}
            else:
                dmap = {v: c for c, v in enumerate(values)}
            block.dictmaps[j] = dmap
        codes = np.empty(len(col), dtype=np.int64)
        data, valid = col.data, col.valid
        for i in range(len(col)):
            if not valid[i]:
                codes[i] = -1
                continue
            v = data[i]
            k = collation_key(v) if ci else v
            c = dmap.get(k)
            if c is None:
                c = len(values)
                dmap[k] = c
                values.append(v)
            codes[i] = c
        return codes, valid & (codes >= 0)

    # -- eviction ------------------------------------------------------------

    def _drop_locked(self, key) -> None:
        _v, _t, block = self._entries.pop(key)
        self._resident[0] -= block.nbytes
        self._pending += block.nbytes

    def _settle(self) -> None:
        with self._mu:
            owed, self._pending = self._pending, 0
        if owed:
            tracker().release(device=owed)

    def drop(self, key, if_block: DeviceBlock | None = None) -> int:
        """Remove one entry (delta staleness, merge refresh). With
        `if_block`, drop only while the entry still holds that exact
        block — a reader invalidating a lagging block must not discard
        a successor another thread just patched/refilled in. -> bytes
        freed."""
        with self._mu:
            ent = self._entries.get(key)
            if ent is None or (if_block is not None and
                               ent[2] is not if_block):
                return 0
            freed = ent[2].nbytes
            self._drop_locked(key)
        metrics.counter(metrics.HBM_CACHE_EVICTIONS)
        self._settle()
        return freed

    def snapshot_table(self, table_id: int) -> list:
        """[(key, fill_version, fill_ts)] for every resident block of
        one table — the delta merge walks this to refresh lagging
        blocks. Device keys are (chunk-cache key, ft codes); the chunk
        key embeds the table id at position 2."""
        with self._mu:
            return [(k, ent[0], ent[1])
                    for k, ent in self._entries.items()
                    if k[0][2] == table_id]

    def shed(self) -> int:
        """Drop every resident block (the OOM action / close path).
        -> bytes freed."""
        with self._mu:
            freed = self._resident[0]
            n = len(self._entries)
            self._entries.clear()
            self._resident[0] = 0
        if n:
            metrics.counter(metrics.HBM_CACHE_EVICTIONS, inc=n)
        if freed:
            tracker().release(device=freed)
        self._settle()
        return freed
