"""HBM-resident columnar region-block cache: hot columns live where the
compute is.

The host-side chunk cache (store/chunk_cache.py) kills the KV-scan +
decode cost of repeated analytical reads, but every execution still
re-paid the host->device transfer unless the SAME chunk object happened
to carry a device memo — an invisible, per-object, unbudgeted residency
that evaporates with the host entry and never helps the streaming path.
BENCH r05 put the device scan path at ~0.23 of the memory roofline
largely on that re-upload. This module is the TiFlash-columnar-replica
analogue one level further down (PAPER.md): the storage node keeps the
PADDED, DICT-ENCODED device arrays per region block resident in HBM,
keyed by (region, schema fingerprint, range) and validated by the
engine's data version, so a repeated TPC-H scan reads straight from HBM
and the fused scan->filter->partial-agg dispatch (store/copr.py) starts
from device-resident columns.

MVCC correctness is inherited from the chunk cache's contract: an entry
records the engine data_version and the fill snapshot ts, and is served
only when the version is unchanged AND read_ts >= fill_ts. Version
bumps on every engine state change (writes, DDL-driven meta mutations,
lock ops), so a stale block can never serve after a write — the
invalidation tests pin this. Fills are allowed exactly where chunk-cache
fills are (no pending locks, snapshot covers every commit), and the
caller passes the HOST entry's fill_ts so both caches agree on validity.

Budget: `tidb_tpu_device_cache_bytes` bounds resident bytes with LRU
eviction (re-read on every lookup AND fill, so SET takes effect on the
next access). Residency is charged to a dedicated memtrack node under
the SERVER root (device ledger), so information_schema.memory_usage and
the server gauges see the cache like any other consumer, and `shed()`
is registered on SERVER's spill-action chain so one call reclaims every
live cache. That chain is ARMED (ROADMAP item 1 delivered): the
admission controller (tidb_tpu/sched.py) drives it when a statement's
projected footprint would push the server past
`tidb_tpu_server_mem_quota` — resident cache blocks are the first thing
shed to make room — and the status port's /shed endpoint fires the same
chain on operator demand.
"""

from __future__ import annotations

import threading
import weakref
from collections import OrderedDict

from tidb_tpu import config, memtrack, metrics

__all__ = ["DeviceBlock", "DeviceCache", "upload_block", "tracker"]


_tracker_lock = threading.Lock()
_tracker: memtrack.MemTracker | None = None   # guarded-by: _tracker_lock

# every live cache, for the single server-wide OOM shed action; weak so
# short-lived test storages don't accumulate forever
_caches: "weakref.WeakSet[DeviceCache]" = \
    weakref.WeakSet()               # guarded-by: _tracker_lock
_shed_registered = False            # guarded-by: _tracker_lock


def tracker() -> memtrack.MemTracker:
    """The shared server-scope tracker node all device caches charge
    (label `hbm-cache`, device ledger)."""
    global _tracker
    with _tracker_lock:
        if _tracker is None:
            _tracker = memtrack.server_node("hbm-cache")
        return _tracker


def _shed_all() -> None:
    """The registered memtrack OOM action: drop every resident block in
    every live cache, returning the hbm-cache ledger to zero."""
    for cache in list(_caches):
        cache.shed()


def _release_resident(resident: list) -> None:
    """GC finalizer: credit back whatever a dead cache still held."""
    freed, resident[0] = resident[0], 0
    if freed:
        tracker().release(device=freed)


def _register(cache: "DeviceCache") -> None:
    global _shed_registered
    with _tracker_lock:
        _caches.add(cache)
        if not _shed_registered:
            memtrack.SERVER.add_spill_action(_shed_all)
            _shed_registered = True


def upload_block(chunk, size: int | None = None):
    """The ONE audited upload site for region columns (lint rule
    `device-cache`): pad + dict-encode + device_put without the
    per-chunk memo (the cache owns residency; a second resident copy
    memoized on the chunk would double HBM). -> (cols, dicts)."""
    from tidb_tpu.ops import runtime
    return runtime.device_put_chunk(chunk, size, memo=False)


class DeviceBlock:
    """One resident region block: the padded device columns exactly as a
    kernel dispatch consumes them, plus the host dictionaries needed to
    decode varlen lanes."""

    __slots__ = ("cols", "dicts", "nrows", "size", "nbytes")

    def __init__(self, cols, dicts, nrows: int, size: int, nbytes: int):
        self.cols = cols
        self.dicts = dicts
        self.nrows = nrows
        self.size = size
        self.nbytes = nbytes


class DeviceCache:
    """LRU over device-resident region blocks, bounded by the
    `tidb_tpu_device_cache_bytes` budget (read per operation, so SET
    takes effect immediately), accounted on the shared hbm-cache
    memtrack node."""

    def __init__(self):
        self._mu = threading.Lock()
        self._entries: OrderedDict = OrderedDict()   # guarded-by: _mu
        # resident bytes live in a one-slot list shared with a GC
        # finalizer: a cache dropped without close() (test storages,
        # abandoned servers) still returns its ledger share, so the
        # hbm-cache node stays exact over the process lifetime
        self._resident = [0]        # guarded-by: _mu
        # bytes dropped under the lock, not settled
        self._pending = 0           # guarded-by: _mu
        weakref.finalize(self, _release_resident, self._resident)
        _register(self)

    @staticmethod
    def key(region, plan, s: bytes, e: bytes):
        """(region, schema fingerprint, range): region id+version, table/
        index ids, the column ids AND their field-type codes (a DDL that
        re-types a column without re-numbering it must not alias), the
        handle flag, and the clamped scan range."""
        from tidb_tpu.store.chunk_cache import ChunkCache
        return (ChunkCache.key(region, plan, s, e),
                tuple(getattr(c.ft, "tp", None) for c in plan.cols))

    def enabled(self) -> bool:
        """Consulted on every agg request. A budget of 0 not only stops
        lookups, it RECLAIMS: resident blocks shed on the next consult,
        so `SET tidb_tpu_device_cache_bytes = 0` actually frees the HBM
        it promises to (the shrink-on-lookup path in get() is
        unreachable once this gate stops all lookups). A transient
        `tidb_tpu_device = 0` keeps residency — flipping the device off
        and on must not cold-start the cache."""
        if config.device_cache_bytes() <= 0:
            if self._resident[0]:
                self.shed()
            return False
        return config.device_enabled()

    def resident_bytes(self) -> int:
        with self._mu:
            return self._resident[0]

    def __len__(self) -> int:
        with self._mu:
            return len(self._entries)

    # -- lookup / fill -------------------------------------------------------

    def get(self, key, data_version: int, read_ts: int) -> DeviceBlock | None:
        """Resident block for `key`, valid for a reader at `read_ts`
        under the current engine `data_version`; a version/ts mismatch
        drops the stale entry (counted as an eviction). The budget is
        re-read here too, so a shrunk `tidb_tpu_device_cache_bytes`
        takes effect on the next lookup — not only at the next fill —
        evicting LRU entries (the served block last) until residency
        fits."""
        budget = config.device_cache_bytes()
        with self._mu:
            ent = self._entries.get(key)
            if ent is None:
                metrics.counter(metrics.HBM_CACHE_MISSES)
                return None
            fill_version, fill_ts, block = ent
            if fill_version != data_version:
                # stale for EVERY reader: drop now, not at LRU pressure
                self._drop_locked(key)
                metrics.counter(metrics.HBM_CACHE_MISSES)
                metrics.counter(metrics.HBM_CACHE_EVICTIONS)
                stale = True
            elif read_ts < fill_ts:
                # too old for THIS reader only — newer snapshots still
                # serve from it, so the entry stays
                metrics.counter(metrics.HBM_CACHE_MISSES)
                return None
            else:
                self._entries.move_to_end(key)
                while self._resident[0] > budget and self._entries:
                    self._drop_locked(next(iter(self._entries)))
                    metrics.counter(metrics.HBM_CACHE_EVICTIONS)
                # the served block stays alive through the returned
                # reference even if it was the one over budget; it is
                # simply no longer resident for the next reader
                metrics.counter(metrics.HBM_CACHE_HITS)
                stale = False
        self._settle()
        return None if stale else block

    def fill(self, key, data_version: int, fill_ts: int,
             chunk) -> DeviceBlock | None:
        """Upload `chunk`'s padded columns and insert. Returns None (no
        upload) when the block alone would exceed the budget. The caller
        owns the MVCC fill contract (see module docstring)."""
        from tidb_tpu.ops.runtime import bucket_size
        budget = config.device_cache_bytes()
        size = bucket_size(max(chunk.num_rows, 1))
        nbytes = memtrack.device_put_bytes(chunk, size)
        if nbytes > budget:
            return None
        cols, dicts = upload_block(chunk, size)
        block = DeviceBlock(cols, dicts, chunk.num_rows, size, nbytes)
        with self._mu:
            if key in self._entries:
                self._drop_locked(key)
            self._entries[key] = (data_version, fill_ts, block)
            self._resident[0] += nbytes
            while self._resident[0] > budget and len(self._entries) > 1:
                old = next(iter(self._entries))
                if old == key:      # never evict the entry just filled
                    break
                self._drop_locked(old)
                metrics.counter(metrics.HBM_CACHE_EVICTIONS)
        # lint: exempt[paired-resource] ownership transfer: residency releases on evict/shed; a GC finalizer backstops dead caches
        tracker().consume(device=nbytes)
        # evictions released under the lock tally in _pending_release;
        # settle them against the shared tracker outside the lock
        self._settle()
        return block

    def get_or_fill(self, key, data_version: int, read_ts: int, chunk,
                    fill_ts: int | None = None) -> DeviceBlock | None:
        """get(); on miss, fill() when `fill_ts` is provided (the
        caller's signal that the MVCC fill conditions hold)."""
        hit = self.get(key, data_version, read_ts)
        if hit is not None:
            return hit
        if fill_ts is None:
            return None
        return self.fill(key, data_version, fill_ts, chunk)

    # -- eviction ------------------------------------------------------------

    def _drop_locked(self, key) -> None:
        _v, _t, block = self._entries.pop(key)
        self._resident[0] -= block.nbytes
        self._pending += block.nbytes

    def _settle(self) -> None:
        with self._mu:
            owed, self._pending = self._pending, 0
        if owed:
            tracker().release(device=owed)

    def shed(self) -> int:
        """Drop every resident block (the OOM action / close path).
        -> bytes freed."""
        with self._mu:
            freed = self._resident[0]
            n = len(self._entries)
            self._entries.clear()
            self._resident[0] = 0
        if n:
            metrics.counter(metrics.HBM_CACHE_EVICTIONS, inc=n)
        if freed:
            tracker().release(device=freed)
        self._settle()
        return freed
