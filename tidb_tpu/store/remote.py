"""Out-of-process storage: the SQL layer talks to storage over sockets.

Reference: /root/reference/store/tikv/client.go:36-95 (gRPC connArray of
16 conns per store address — the distributed communication backend),
tikvrpc/tikvrpc.go:31-53 (typed command envelope), region_request.go
(network-error handling + retry). The defining property this restores is
the reference's architecture: a STATELESS SQL layer connected by RPC to a
storage cluster that owns the data, the coprocessor compute, and the TSO.

Wire format: length-prefixed frames, 1-byte status, then a typed
payload encoded by store/wire.py — a closed tag-length-value contract
mirroring the reference's protobuf envelope (tikvrpc.CmdType +
kvproto/tipb messages). Requests carry `u16 Cmd` + an args/kwargs
tuple; responses carry the result value or a registered typed error.
No pickle anywhere on the wire path: decoding cannot execute code, and
malformed frames raise WireError (fuzzed in tests/test_wire.py).
On-disk snapshots (trusted, local files we wrote) still use pickle.

Failure semantics (region_request.go's network-error split):
  * connection failure BEFORE the request is written -> retry on a fresh
    connection (nothing executed).
  * failure while awaiting the response -> idempotent commands (reads,
    coprocessor, TSO, region lookup) retry transparently; mutating
    commands surface TimeoutError_ so the 2PC layer runs its
    undetermined-commit protocol (2pc.go:421-431).
"""

from __future__ import annotations

import argparse
import io
import os
import pickle
import signal
import socket
import struct
import threading
import time

from tidb_tpu import kv
from tidb_tpu.mockstore.rpc import TimeoutError_
from tidb_tpu.store import wire

__all__ = ["StorageServer", "RemoteStorage", "connect", "serve_main"]

_STATUS_OK = 0
_STATUS_ERR = 1

# commands safe to re-send after an indeterminate failure
_IDEMPOTENT = {"kv_get", "kv_batch_get", "kv_scan", "kv_scan_lock",
               "coprocessor", "region_by_key", "tso", "kv_cleanup",
               "snapshot_batch_get", "ping", "regions_snapshot",
               # raw ops are idempotent by definition (no MVCC, repeat
               # puts/deletes converge); mvcc_* are pure reads
               "raw_get", "raw_batch_get", "raw_scan", "raw_put",
               "raw_batch_put", "raw_delete", "raw_delete_range",
               "mvcc_by_key", "mvcc_by_start_ts"}

MAX_CONNS = 16   # ref: client.go:37 MaxConnectionCount


def _send_frame(sock: socket.socket, status: int, payload: bytes) -> None:
    sock.sendall(struct.pack("<IB", len(payload) + 1, status) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = io.BytesIO()
    got = 0
    while got < n:
        chunk = sock.recv(n - got)
        if not chunk:
            raise ConnectionError("peer closed")
        buf.write(chunk)
        got += len(chunk)
    return buf.getvalue()


def _recv_frame(sock: socket.socket) -> tuple[int, bytes]:
    head = _recv_exact(sock, 5)
    (length, status) = struct.unpack("<IB", head)
    return status, _recv_exact(sock, length - 1)


# ---------------------------------------------------------------------------
# server side

class StorageServer:
    """Hosts a full storage node (cluster topology + MVCC engine + RPC
    shim + coprocessor with its device kernels + columnar chunk cache)
    behind a socket. One thread per connection; the shim's own locking
    provides consistency exactly as with in-process threads."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 snapshot_path: str | None = None):
        from tidb_tpu.store.copr import cop_handler
        from tidb_tpu.store.storage import MockStorage, new_mock_storage
        self.snapshot_path = snapshot_path
        if snapshot_path and os.path.exists(snapshot_path):
            with open(snapshot_path, "rb") as f:
                cluster, engine = pickle.load(f)
            self.storage = MockStorage(cluster, engine)
        else:
            self.storage = new_mock_storage()
        self.storage.shim.install_cop_handler(cop_handler(self.storage))
        self._listener = socket.create_server((host, port))
        self.port = self._listener.getsockname()[1]
        self._closing = threading.Event()
        self._threads: set = set()
        self._mu = threading.Lock()

    def start(self) -> None:
        t = threading.Thread(target=self._accept, daemon=True,
                             name="storage-accept")
        t.start()

    def _accept(self) -> None:
        while not self._closing.is_set():
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            t = threading.Thread(target=self._serve, args=(sock,),
                                 daemon=True, name="storage-conn")
            with self._mu:
                self._threads.add(t)
            t.start()

    @staticmethod
    def _validate_request(req):
        """Typed request envelope: (cmd:int, args:tuple, kwargs:dict)."""
        if not (isinstance(req, tuple) and len(req) == 3):
            raise wire.WireError("request must be (cmd, args, kwargs)")
        cmd, args, kwargs = req
        try:
            cmd = wire.Cmd(cmd)
        except ValueError:
            raise wire.WireError(f"unknown command {cmd!r}") from None
        if cmd not in wire.METHOD_BY_CMD:
            raise wire.WireError(f"unroutable command {cmd!r}")
        if not isinstance(args, tuple) or not isinstance(kwargs, dict):
            raise wire.WireError("bad args/kwargs")
        if any(not isinstance(k, str) for k in kwargs):
            raise wire.WireError("kwargs keys must be strings")
        return cmd, args, kwargs

    def _dispatch(self, method: str, args: tuple, kwargs: dict):
        st = self.storage
        if method == "ping":
            return "pong"
        if method == "tso":
            return st.cluster.tso()
        if method == "region_by_key":
            return st.cluster.region_by_key(*args)
        if method == "regions_snapshot":
            return list(st.cluster._regions.values())
        if method == "split":
            return st.cluster.split(*args)
        if method == "split_table":
            return st.cluster.split_table(*args, **kwargs)
        if method == "bulk_import":
            return st.engine.bulk_import(*args)
        if method == "snapshot_batch_get":
            # helper: batch_get without a region ctx (handles resolved
            # client-side into per-region calls normally; this is the
            # bulk row-fetch path of IndexLookUp/IndexJoin)
            raise kv.KVError("use kv_batch_get with a region ctx")
        fn = getattr(self.storage.shim, method, None)
        if fn is None or method.startswith("_") or not callable(fn):
            raise kv.KVError(f"unknown storage method {method!r}")
        return fn(*args, **kwargs)

    def _serve(self, sock: socket.socket) -> None:
        try:
            while True:
                try:
                    _status, payload = _recv_frame(sock)
                except (ConnectionError, OSError):
                    return
                try:
                    req = wire.decode_frame_payload(payload)
                    cmd, args, kwargs = self._validate_request(req)
                    method = wire.METHOD_BY_CMD[cmd]
                    result = self._dispatch(method, args, kwargs)
                    out, status = wire.encode(result), _STATUS_OK
                except wire.WireError as e:
                    # malformed frame: reject loudly, keep serving
                    out = wire.encode(kv.KVError(f"bad request: {e}"))
                    status = _STATUS_ERR
                except Exception as e:  # noqa: BLE001 - typed errors ride back
                    try:
                        out, status = wire.encode(e), _STATUS_ERR
                    except wire.WireError:
                        out = wire.encode(
                            kv.KVError(f"{type(e).__name__}: {e}"))
                        status = _STATUS_ERR
                try:
                    _send_frame(sock, status, out)
                except (ConnectionError, OSError):
                    return
        finally:
            with self._mu:
                self._threads.discard(threading.current_thread())
            try:
                sock.close()
            except OSError:
                pass

    def save_snapshot(self) -> None:
        if not self.snapshot_path:
            return
        tmp = self.snapshot_path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump((self.storage.cluster, self.storage.engine), f)
        os.replace(tmp, self.snapshot_path)

    def close(self) -> None:
        self._closing.set()
        try:
            self._listener.close()
        except OSError:
            pass
        self.save_snapshot()


# ---------------------------------------------------------------------------
# client side

class _Conn:
    def __init__(self, addr):
        self.sock = socket.create_connection(addr, timeout=30)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    def call(self, method: str, args: tuple, kwargs: dict):
        cmd = wire.CMD_BY_METHOD.get(method)
        if cmd is None:
            raise kv.KVError(f"method {method!r} has no wire command")
        payload = wire.encode((int(cmd), tuple(args), dict(kwargs)))
        _send_frame(self.sock, _STATUS_OK, payload)
        status, body = _recv_frame(self.sock)
        result = wire.decode_frame_payload(body)
        if status == _STATUS_ERR:
            if isinstance(result, BaseException):
                raise result
            raise kv.KVError(f"storage error: {result!r}")
        return result

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


class RemoteClient:
    """Connection pool + failure translation (ref: client.go connArray +
    region_request.go onSendFail)."""

    def __init__(self, addr, max_conns: int = MAX_CONNS,
                 retry_window: float = 10.0):
        self.addr = addr
        self.retry_window = retry_window
        self._pool: list[_Conn] = []
        self._sema = threading.Semaphore(max_conns)
        self._mu = threading.Lock()

    def _checkout(self) -> _Conn:
        with self._mu:
            if self._pool:
                return self._pool.pop()
        return _Conn(self.addr)

    def _checkin(self, conn: _Conn) -> None:
        with self._mu:
            if len(self._pool) < MAX_CONNS:
                self._pool.append(conn)
                return
        conn.close()

    def call(self, method: str, *args, **kwargs):
        self._sema.acquire()
        try:
            return self._call_inner(method, args, kwargs)
        finally:
            self._sema.release()

    def _call_inner(self, method: str, args, kwargs):
        deadline = time.monotonic() + self.retry_window
        idempotent = method in _IDEMPOTENT
        sent_once = False
        while True:
            try:
                conn = self._checkout()
            except OSError as e:
                if time.monotonic() < deadline:
                    time.sleep(0.1)
                    continue    # storage may be restarting: keep dialing
                raise kv.ServerBusyError(
                    f"storage unreachable at {self.addr}: {e}") from None
            try:
                result = conn.call(method, args, kwargs)
            except (ConnectionError, OSError, wire.WireError,
                    EOFError) as e:
                conn.close()
                sent_once = True
                if idempotent and time.monotonic() < deadline:
                    time.sleep(0.05)
                    continue
                if idempotent:
                    raise kv.ServerBusyError(
                        f"storage i/o failure: {e}") from None
                # a mutating command may or may not have executed
                raise TimeoutError_(
                    f"storage i/o failure mid-request: {e}") from None
            self._checkin(conn)
            return result

    def close(self) -> None:
        with self._mu:
            for c in self._pool:
                c.close()
            self._pool.clear()


class _RemotePD:
    """Cluster-lookalike for RegionCache + PDOracle: region routing and
    TSO served by the storage process (the PD role)."""

    def __init__(self, client: RemoteClient):
        self.client = client

    def region_by_key(self, key: bytes):
        return self.client.call("region_by_key", key)

    def tso(self) -> int:
        return self.client.call("tso")

    def all_regions(self):
        return self.client.call("regions_snapshot")

    # test/benchmark topology control
    def split(self, key: bytes):
        return self.client.call("split", key)

    def split_table(self, table_id: int, count: int,
                    max_handle: int = 1 << 20):
        return self.client.call("split_table", table_id, count,
                                max_handle=max_handle)


class _RemoteShim:
    """RPCShim-lookalike: every kv_*/coprocessor call rides the wire."""

    def __init__(self, client: RemoteClient):
        self.client = client

    def __getattr__(self, name: str):
        if name.startswith(("kv_", "raw_", "mvcc_")) or \
                name in ("coprocessor", "split_region"):
            def call(*args, **kwargs):
                return self.client.call(name, *args, **kwargs)
            return call
        raise AttributeError(name)


class _RemoteEngine:
    """Offline-import surface of the remote engine (bulkload)."""

    def __init__(self, client: RemoteClient):
        self.client = client

    def bulk_import(self, pairs, start_ts: int, commit_ts: int) -> int:
        return self.client.call("bulk_import", list(pairs), start_ts,
                                commit_ts)


class RemoteStorage(kv.Storage):
    """kv.Storage whose shim/PD/TSO live in another process. Drop-in for
    MockStorage at the session layer: txns, snapshots, coprocessor
    fan-out, GC all run their existing client logic over the wire."""

    def __init__(self, addr):
        from tidb_tpu.store.oracle import PDOracle
        from tidb_tpu.store.region_cache import RegionCache
        from tidb_tpu.store.txn import KVTxn, LockResolver, TxnSnapshot
        self._txn_cls = KVTxn
        self._snap_cls = TxnSnapshot
        self.rpc = RemoteClient(addr)
        self.pd = _RemotePD(self.rpc)
        self.cluster = self.pd              # topology ops for tests/bench
        self.shim = _RemoteShim(self.rpc)
        self.engine = _RemoteEngine(self.rpc)
        self.region_cache = RegionCache(self.pd)
        self.oracle = PDOracle(self.pd)
        self.resolver = LockResolver(self.shim, self.region_cache,
                                     self.oracle)
        self.async_commit_secondaries = True
        self._client = None
        self.safepoint = 0

    def begin(self, start_ts: int | None = None):
        return self._txn_cls(self, start_ts if start_ts is not None
                             else self.oracle.get_timestamp())

    def snapshot(self, ts: int):
        return self._snap_cls(self.shim, self.region_cache, self.resolver,
                              ts, storage=self)

    def current_ts(self) -> int:
        return self.oracle.get_timestamp()

    def check_visibility(self, ts: int) -> None:
        if ts < self.safepoint:
            raise kv.GCTooEarlyError(
                f"snapshot ts {ts} is below GC safepoint {self.safepoint}")

    def update_safepoint(self, sp: int) -> None:
        self.safepoint = max(self.safepoint, sp)

    def client(self):
        if self._client is None:
            from tidb_tpu.store.copr import CopClient
            self._client = CopClient(self)
        return self._client

    def ping(self) -> bool:
        return self.rpc.call("ping") == "pong"

    def close(self) -> None:
        self.oracle.close()
        self.rpc.close()


def connect(host: str, port: int) -> RemoteStorage:
    return RemoteStorage((host, port))


# ---------------------------------------------------------------------------
# process entry: python -m tidb_tpu.store.remote --port N

def serve_main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tidb_tpu.store.remote",
                                description="storage node process")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--snapshot", default=None,
                   help="state snapshot file (loaded at start, saved on "
                        "graceful shutdown)")
    args = p.parse_args(argv)
    server = StorageServer(args.host, args.port,
                           snapshot_path=args.snapshot)
    server.start()
    print(f"storage listening on {args.host}:{server.port}", flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    server.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(serve_main())
