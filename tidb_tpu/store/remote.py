"""Out-of-process storage: the SQL layer talks to storage over sockets.

Reference: /root/reference/store/tikv/client.go:36-95 (gRPC connArray of
16 conns per store address — the distributed communication backend),
tikvrpc/tikvrpc.go:31-53 (typed command envelope), region_request.go
(network-error handling + retry). The defining property this restores is
the reference's architecture: a STATELESS SQL layer connected by RPC to a
storage cluster that owns the data, the coprocessor compute, and the TSO.

Wire format: length-prefixed frames, 1-byte status, then a typed
payload encoded by store/wire.py — a closed tag-length-value contract
mirroring the reference's protobuf envelope (tikvrpc.CmdType +
kvproto/tipb messages). Requests carry `u16 Cmd` + an args/kwargs
tuple; responses carry the result value or a registered typed error.
No pickle anywhere on the wire path: decoding cannot execute code, and
malformed frames raise WireError (fuzzed in tests/test_wire.py; the
no-pickle invariant is pinned by the `wire-discipline` lint rule —
tidb_tpu/lint, see docs/LINTS.md). On-disk snapshots (trusted, local
files we wrote) live in store/snapshot.py.

Streamed coprocessor replies (Cmd.COP_STREAM) are multi-frame: the
server answers one request with STATUS_STREAM_FRAME frames under the
credit-based flow control of store/wire.py — the request carries an
initial window, the server blocks at zero credit until the client
grants more, so a slow consumer backpressures the storage node instead
of buffering whole regions on either side.

Failure semantics (region_request.go's network-error split):
  * connection failure BEFORE the request is written -> retry on a fresh
    connection (nothing executed).
  * failure while awaiting the response -> idempotent commands (reads,
    coprocessor, TSO, region lookup) retry transparently; mutating
    commands surface TimeoutError_ so the 2PC layer runs its
    undetermined-commit protocol (2pc.go:421-431).
"""

from __future__ import annotations

import argparse
import io
import os
import signal
import socket
import struct
import threading
import time

from tidb_tpu import kv
from tidb_tpu.mockstore.rpc import TimeoutError_
from tidb_tpu.store import wire

__all__ = ["StorageServer", "RemoteStorage", "connect", "serve_main"]

_STATUS_OK = wire.STATUS_OK
_STATUS_ERR = wire.STATUS_ERR
_STATUS_OK_TRACED = wire.STATUS_OK_TRACED   # payload = (result, spans)

# commands safe to re-send after an indeterminate failure
_IDEMPOTENT = {"kv_get", "kv_batch_get", "kv_scan", "kv_scan_lock",
               "coprocessor", "coprocessor_stream", "journal_window",
               "region_by_key", "tso", "kv_cleanup",
               "snapshot_batch_get", "ping", "regions_snapshot",
               # raw ops are idempotent by definition (no MVCC, repeat
               # puts/deletes converge); mvcc_* are pure reads
               "raw_get", "raw_batch_get", "raw_scan", "raw_put",
               "raw_batch_put", "raw_delete", "raw_delete_range",
               "mvcc_by_key", "mvcc_by_start_ts"}

MAX_CONNS = 16   # ref: client.go:37 MaxConnectionCount

# commands that change durable state: replicated to the backup (the
# "log" of primary/backup log shipping). Everything else is a read.
_MUTATING = {"kv_prewrite", "kv_commit", "kv_batch_rollback",
             "kv_resolve_lock", "kv_cleanup", "kv_delete_range", "kv_gc",
             "raw_put", "raw_batch_put", "raw_delete", "raw_delete_range",
             "split", "split_table", "split_region", "bulk_import"}


def _send_frame(sock: socket.socket, status: int, payload: bytes) -> None:
    sock.sendall(struct.pack("<IB", len(payload) + 1, status) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = io.BytesIO()
    got = 0
    while got < n:
        chunk = sock.recv(n - got)
        if not chunk:
            raise ConnectionError("peer closed")
        buf.write(chunk)
        got += len(chunk)
    return buf.getvalue()


def _recv_frame(sock: socket.socket) -> tuple[int, bytes]:
    head = _recv_exact(sock, 5)
    (length, status) = struct.unpack("<IB", head)
    return status, _recv_exact(sock, length - 1)


# ---------------------------------------------------------------------------
# server side

def _adopt_origin(root, flags: dict) -> dict | None:
    """Map a request's forward-propagated trace context (wire.FLAG_ORIGIN)
    onto the local handler root: the originating statement's sampled/
    forced retention decision applies to the storage-side tree too, and
    the origin id/member land as tags so the retained record — and the
    span tree shipped back — carry the fleet-wide join key.
    -> the validated origin dict (None when absent/malformed)."""
    origin = flags.get(wire.FLAG_ORIGIN)
    if not isinstance(origin, dict) or "trace_id" not in origin:
        return None
    try:
        root.tags["origin_trace_id"] = int(origin["trace_id"])
    except (TypeError, ValueError):
        return None
    root.sampled = bool(origin.get("sampled"))
    root.forced = bool(origin.get("forced"))
    root.tags["origin_member"] = str(origin.get("member", ""))
    return origin


class StorageServer:
    """Hosts a full storage node (cluster topology + MVCC engine + RPC
    shim + coprocessor with its device kernels + columnar chunk cache)
    behind a socket. One thread per connection; the shim's own locking
    provides consistency exactly as with in-process threads."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 snapshot_path: str | None = None,
                 role: str = "primary", backup_addr=None,
                 primary_addr=None):
        from tidb_tpu.store import snapshot as snapshot_io
        from tidb_tpu.store.copr import cop_handler
        from tidb_tpu.store.storage import MockStorage, new_mock_storage
        from tidb_tpu.store.stream import cop_stream_handler
        self.snapshot_path = snapshot_path
        if snapshot_path and os.path.exists(snapshot_path):
            cluster, engine = snapshot_io.load(snapshot_path)
            self.storage = MockStorage(cluster, engine)
        else:
            self.storage = new_mock_storage()
        self.storage.shim.install_cop_handler(cop_handler(self.storage))
        self.storage.shim.install_cop_stream_handler(
            cop_stream_handler(self.storage))
        # -- replication (ref: the Raft-replicated TiKV store; here a
        # synchronous primary/backup log-shipping analogue) ---------------
        self.role = role
        self._ship_mu = threading.Lock()   # serializes apply+ship order
        self._backup: _Conn | None = None  # guarded-by: _ship_mu
        self._backup_addr = backup_addr
        self._backup_dead = False          # guarded-by: _ship_mu
        if role == "backup" and primary_addr is not None:
            self._attach_to_primary(primary_addr)
        self._listener = socket.create_server((host, port))
        self.port = self._listener.getsockname()[1]
        self._closing = threading.Event()
        self._threads: set = set()         # guarded-by: _mu
        self._mu = threading.Lock()

    # -- replication ---------------------------------------------------------

    def _attach_to_primary(self, primary_addr) -> None:
        """Pull a full state snapshot so a fresh backup starts in sync
        (the primary then ships every mutation as it happens)."""
        conn = _Conn(primary_addr)
        try:
            state = conn.call("repl_snapshot", (), {})
            self._install_state(state)
        finally:
            conn.close()

    def _export_state(self):
        # _ship_mu orders the export against the apply+ship critical
        # section: a mutation is either fully (applied AND shipped)
        # before the snapshot, or entirely after it — never replayed on
        # top of a snapshot that already contains it
        with self._ship_mu:
            return self._export_state_locked()

    def _export_state_locked(self):
        cl, en = self.storage.cluster, self.storage.engine
        with cl._mu, en._mu:
            return {
                "id": cl._id,
                "stores": list(cl.stores.values()),
                "regions": list(cl._regions.values()),
                "tso_physical": cl._tso_physical,
                "tso_logical": cl._tso_logical,
                "entries": list(en._entries.items()),
            }

    def _install_state(self, st: dict) -> None:
        from tidb_tpu.util.sorteddict import SortedDict
        cl, en = self.storage.cluster, self.storage.engine
        with cl._mu, en._mu:
            cl._id = st["id"]
            cl.stores = {s.id: s for s in st["stores"]}
            cl._regions = SortedDict(
                {r.start: r for r in st["regions"]})
            cl._tso_physical = st["tso_physical"]
            cl._tso_logical = st["tso_logical"]
            en._entries = SortedDict(
                {k: e for k, e in st["entries"]})
            en._locked_keys = {k for k, e in st["entries"]
                               if e.lock is not None}

    _RESYNC_INTERVAL = 1.0   # seconds between re-attach attempts

    def _start_resync_thread(self) -> None:
        """Degraded mode: a PERMANENT daemon monitor dials the backup
        OFF the write path (a blocking connect under _ship_mu would
        stall every mutation); once the backup answers, it takes
        _ship_mu only for the consistent snapshot push. The monitor
        never exits while the server lives, so there is no window where
        a dying thread suppresses the start of its replacement."""
        if getattr(self, "_resync_thread", None) is not None and \
                self._resync_thread.is_alive():
            return

        def loop():
            while not self._closing.is_set():
                time.sleep(self._RESYNC_INTERVAL)
                if not self._backup_dead:
                    continue
                try:
                    conn = _Conn(self._backup_addr, timeout=5)
                except OSError:
                    continue
                try:
                    with self._ship_mu:
                        if not self._backup_dead:
                            continue
                        conn.call("repl_install",
                                  (self._export_state_locked(),), {})
                        self._backup_dead = False
                    print("storage: backup re-synced, resuming "
                          "replication", flush=True)
                except (ConnectionError, OSError, wire.WireError,
                        kv.KVError):
                    continue
                finally:
                    conn.close()

        self._resync_thread = threading.Thread(
            target=loop, daemon=True, name="storage-resync")
        self._resync_thread.start()

    def _ship(self, method: str, args: tuple, kwargs: dict) -> None:
        """Synchronously replicate one applied mutation. Called under
        _ship_mu, so the backup applies in exactly primary order. If the
        backup is unreachable (or rejects a replay) the primary degrades
        to solo and RE-SYNCS it with a full state push as soon as it
        answers again (_start_resync_thread, off the write path) — the
        unreplicated window is bounded by the outage plus one resync. Writes acked during
        that window are lost only if the primary ALSO dies before the
        resync lands (the inherent 2-node degraded-mode caveat; a quorum
        design needs 3 nodes)."""
        if self._backup_dead or self._backup_addr is None:
            return
        cl = self.storage.cluster
        watermark = (cl._tso_physical << 18) | cl._tso_logical
        try:
            if self._backup is None:
                self._backup = _Conn(self._backup_addr)
            self._backup.call("repl_apply",
                              (method, args, kwargs, watermark), {})
        except (ConnectionError, OSError, wire.WireError,
                kv.KVError) as e:
            # incl. KVError: a backup that rejects a replay has diverged
            # and needs the full-state resync, and the client's write —
            # already applied locally — must NOT fail because of it
            if self._backup is not None:
                self._backup.close()
                self._backup = None
            self._backup_dead = True
            print(f"storage: backup unreachable, degrading to solo "
                  f"(re-sync thread running): {e}", flush=True)
            self._start_resync_thread()


    def _repl_apply(self, method: str, args: tuple, kwargs: dict,
                    watermark: int) -> None:
        if self.role != "backup":
            raise kv.KVError("repl_apply on a non-backup node")
        if method not in _MUTATING:
            raise kv.KVError(f"refusing to replay {method!r}")
        cl = self.storage.cluster
        with cl._mu:
            # track the primary's TSO so a promotion never goes backward
            if (watermark >> 18) > cl._tso_physical:
                cl._tso_physical = watermark >> 18
                cl._tso_logical = watermark & ((1 << 18) - 1)
        self._dispatch(method, args, kwargs)

    def _repl_promote(self) -> str:
        """Backup -> primary (failover). TSO is bumped past everything
        the dead primary could have issued."""
        if self.role == "primary":
            return "already-primary"
        cl = self.storage.cluster
        with cl._mu:
            cl._tso_physical = max(cl._tso_physical,
                                   int(time.time() * 1000)) + 1
            cl._tso_logical = 0
        self.role = "primary"
        return "promoted"

    def start(self) -> None:
        t = threading.Thread(target=self._accept, daemon=True,
                             name="storage-accept")
        t.start()

    def _accept(self) -> None:
        while not self._closing.is_set():
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            t = threading.Thread(target=self._serve, args=(sock,),
                                 daemon=True, name="storage-conn")
            with self._mu:
                self._threads.add(t)
            t.start()

    @staticmethod
    def _validate_request(req):
        """Typed request envelope: (cmd:int, args:tuple, kwargs:dict
        [, flags:dict]) — flags carry cross-process metadata like the
        trace-propagation bit."""
        if not (isinstance(req, tuple) and len(req) in (3, 4)):
            raise wire.WireError("request must be (cmd, args, kwargs"
                                 "[, flags])")
        cmd, args, kwargs = req[:3]
        flags = req[3] if len(req) == 4 else {}
        try:
            cmd = wire.Cmd(cmd)
        except ValueError:
            raise wire.WireError(f"unknown command {cmd!r}") from None
        if cmd not in wire.METHOD_BY_CMD:
            raise wire.WireError(f"unroutable command {cmd!r}")
        if not isinstance(args, tuple) or not isinstance(kwargs, dict) \
                or not isinstance(flags, dict):
            raise wire.WireError("bad args/kwargs/flags")
        if any(not isinstance(k, str) for k in kwargs):
            raise wire.WireError("kwargs keys must be strings")
        return cmd, args, kwargs, flags

    def _serve_call(self, method: str, args: tuple, kwargs: dict):
        """Top-level command entry: role gate + replication shipping."""
        if method == "ping":
            return "pong"
        if method == "repl_hello":
            return {"role": self.role,
                    "backup_dead": self._backup_dead}
        if method == "repl_apply":
            return self._repl_apply(*args)
        if method == "repl_snapshot":
            return self._export_state()
        if method == "repl_install":
            if self.role != "backup":
                raise kv.KVError("repl_install on a non-backup node")
            self._install_state(args[0])
            return "installed"
        if method == "repl_promote":
            return self._repl_promote()
        if self.role == "backup":
            # data commands only run on the primary; leader_store=-1 is
            # the "this is a replication backup" sentinel the client's
            # failover logic keys on (ref: NotLeader region errors)
            raise kv.NotLeaderError(0, -1)
        if method in _MUTATING and self._backup_addr is not None:
            # the ship lock serializes apply+ship so the backup applies
            # in primary order; standalone servers skip it entirely
            with self._ship_mu:
                result = self._dispatch(method, args, kwargs)
                self._ship(method, args, kwargs)
                return result
        return self._dispatch(method, args, kwargs)

    def _dispatch(self, method: str, args: tuple, kwargs: dict):
        st = self.storage
        if method == "ping":
            return "pong"
        if method == "tso":
            return st.cluster.tso()
        if method == "region_by_key":
            return st.cluster.region_by_key(*args)
        if method == "regions_snapshot":
            return list(st.cluster._regions.values())
        if method == "split":
            return st.cluster.split(*args)
        if method == "split_table":
            return st.cluster.split_table(*args, **kwargs)
        if method == "bulk_import":
            return st.engine.bulk_import(*args)
        if method == "snapshot_batch_get":
            # helper: batch_get without a region ctx (handles resolved
            # client-side into per-region calls normally; this is the
            # bulk row-fetch path of IndexLookUp/IndexJoin)
            raise kv.KVError("use kv_batch_get with a region ctx")
        fn = getattr(self.storage.shim, method, None)
        if fn is None or method.startswith("_") or not callable(fn):
            raise kv.KVError(f"unknown storage method {method!r}")
        return fn(*args, **kwargs)

    def _serve_stream(self, sock: socket.socket, args: tuple,
                      kwargs: dict, flags: dict | None = None) -> bool:
        """Serve one COP_STREAM request: StreamFrames under credit flow
        control (wire.py). Blocks — not buffers — when the client's
        credit window is exhausted; the blocking recv IS the
        backpressure. A traced request runs under a local root span
        whose finished tree rides back ON THE END FRAME (streams bypass
        the STATUS_OK_TRACED envelope). -> False when the connection
        died and the serve loop must exit."""
        kwargs = dict(kwargs)
        credit = kwargs.pop("credit", None)
        root = None
        origin = None
        if flags and flags.get(wire.FLAG_TRACE):
            from tidb_tpu import trace as _trace
            root = _trace.begin("storage:coprocessor_stream")
            origin = _adopt_origin(root, flags)
        gen = None
        try:
            gate = wire.CreditGate(credit if credit is not None else 4)
            gen = self._serve_call("coprocessor_stream", args, kwargs)
        except Exception as e:  # noqa: BLE001 — typed errors ride back
            if root is not None:
                from tidb_tpu import trace as _trace
                _trace.end(root)    # unpin the thread-local trace root
                _trace.finish_statement(root, "storage:coprocessor_stream",
                                        origin=origin)
                root = None
            return self._stream_abort(sock, e)
        try:
            it = iter(gen)
            while True:
                try:
                    frame = next(it)
                except StopIteration:
                    break
                except Exception as e:  # noqa: BLE001 — typed mid-stream
                    # mid-stream abort: the client may have grants in
                    # flight we cannot count, so the connection dies
                    # with the stream (the client closes its end too)
                    self._stream_abort(sock, e)
                    return gate.sent == 0
                if gate.credit <= 0:
                    # one stall EPISODE (matching BoundedFrameQueue's
                    # accounting), however many grant frames it takes
                    from tidb_tpu.store.stream import note_credit_stall
                    note_credit_stall()
                    while gate.credit <= 0:
                        status, payload = _recv_frame(sock)
                        gate.feed_grant(status, payload)
                _send_frame(sock, wire.STATUS_STREAM_FRAME,
                            wire.encode(frame))
                gate.consume()
            if root is not None:
                from tidb_tpu import trace as _trace
                _trace.end(root)
                _trace.finish_statement(root, "storage:coprocessor_stream",
                                        origin=origin)
                end_payload = wire.encode(root.to_dict())
                root = None
            else:
                end_payload = wire.encode(None)
            _send_frame(sock, wire.STATUS_STREAM_END, end_payload)
            # absorb the trailing grants (one per consumed frame) so the
            # next request on this connection isn't misread as a grant.
            # NO deadline: the client sends each grant only after its
            # consumer finishes that frame, and a consumer stall (first
            # XLA compile runs minutes) is legitimate — blocking here is
            # the same idle state this thread would be in awaiting the
            # next request, and a vanished client surfaces as
            # ConnectionError either way
            while gate.outstanding > 0:
                status, payload = _recv_frame(sock)
                gate.feed_grant(status, payload)
            return True
        except (ConnectionError, OSError):
            return False        # client went away mid-stream
        except wire.WireError as e:
            # peer protocol violation (bogus grant, etc.): abort loudly;
            # framing sync is unknown, so the connection must die
            self._stream_abort(sock, kv.KVError(f"stream protocol: {e}"))
            return False
        finally:
            if root is not None:
                from tidb_tpu import trace as _trace
                _trace.end(root)    # error/disconnect path: unpin, and
                _trace.finish_statement(root, "storage:coprocessor_stream",
                                        origin=origin)  # still joinable
            if gen is not None and hasattr(gen, "close"):
                gen.close()

    @staticmethod
    def _stream_abort(sock: socket.socket, e: BaseException) -> bool:
        """Terminate a stream with a typed error frame; the connection
        returns to request/response state. -> serve-loop liveness."""
        try:
            out = wire.encode(e)
        except wire.WireError:
            out = wire.encode(kv.KVError(f"{type(e).__name__}: {e}"))
        try:
            _send_frame(sock, wire.STATUS_ERR, out)
            return True
        except (ConnectionError, OSError):
            return False

    def _serve(self, sock: socket.socket) -> None:
        try:
            while True:
                try:
                    _status, payload = _recv_frame(sock)
                except (ConnectionError, OSError):
                    return
                try:
                    req = wire.decode_frame_payload(payload)
                    cmd, args, kwargs, flags = self._validate_request(req)
                    method = wire.METHOD_BY_CMD[cmd]
                    if cmd == wire.Cmd.COP_STREAM:
                        if self._serve_stream(sock, args, kwargs, flags):
                            continue
                        return
                    if flags.get(wire.FLAG_TRACE):
                        # cross-process span propagation: run under a
                        # local root and ship the finished tree back for
                        # the client to graft into its statement trace
                        from tidb_tpu import trace
                        # lint: exempt[trace-names] cross-process storage root: the method name is wire data; these roots graft via attach_remote and retain only origin-stamped
                        root = trace.begin(f"storage:{method}")
                        origin = _adopt_origin(root, flags)
                        try:
                            result = self._serve_call(method, args,
                                                      kwargs)
                        finally:
                            trace.end(root)
                            # store-plane retention: a sampled/forced/
                            # slow handler root keeps its tree in THIS
                            # process's ring, stamped with the
                            # originating statement's fleet trace id —
                            # the record cluster_statement_traces and
                            # /fleet/trace join on
                            trace.finish_statement(
                                root, f"storage:{method}",
                                origin=origin)
                        out = wire.encode((result, root.to_dict()))
                        status = _STATUS_OK_TRACED
                    else:
                        result = self._serve_call(method, args, kwargs)
                        out, status = wire.encode(result), _STATUS_OK
                except wire.WireError as e:
                    # malformed frame: reject loudly, keep serving
                    out = wire.encode(kv.KVError(f"bad request: {e}"))
                    status = _STATUS_ERR
                except Exception as e:  # noqa: BLE001 - typed errors ride back
                    try:
                        out, status = wire.encode(e), _STATUS_ERR
                    except wire.WireError:
                        out = wire.encode(
                            kv.KVError(f"{type(e).__name__}: {e}"))
                        status = _STATUS_ERR
                try:
                    _send_frame(sock, status, out)
                except (ConnectionError, OSError):
                    return
        finally:
            with self._mu:
                self._threads.discard(threading.current_thread())
            try:
                sock.close()
            except OSError:
                pass

    def save_snapshot(self) -> None:
        if not self.snapshot_path:
            return
        from tidb_tpu.store import snapshot as snapshot_io
        snapshot_io.save(self.snapshot_path, self.storage.cluster,
                         self.storage.engine)

    def close(self) -> None:
        self._closing.set()
        try:
            self._listener.close()
        except OSError:
            pass
        self.save_snapshot()


# ---------------------------------------------------------------------------
# client side

class _Conn:
    def __init__(self, addr, timeout: float = 30):
        self.sock = socket.create_connection(addr, timeout=timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    @staticmethod
    def _trace_flags(trace) -> dict:
        """Request flags of a traced call: the trace bit plus the
        originating statement's forward context (fleet-unique trace id
        + retention flags + member id) so the store plane can stamp
        whatever it retains with the statement that caused it."""
        flags = {wire.FLAG_TRACE: True}
        o = trace.origin()
        if o is not None:
            flags[wire.FLAG_ORIGIN] = o
        return flags

    def call(self, method: str, args: tuple, kwargs: dict):
        from tidb_tpu import trace
        cmd = wire.CMD_BY_METHOD.get(method)
        if cmd is None:
            raise kv.KVError(f"method {method!r} has no wire command")
        req = (int(cmd), tuple(args), dict(kwargs))
        if trace.active():
            req = req + (self._trace_flags(trace),)
        payload = wire.encode(req)
        _send_frame(self.sock, _STATUS_OK, payload)
        status, body = _recv_frame(self.sock)
        result = wire.decode_frame_payload(body)
        if status == _STATUS_ERR:
            if isinstance(result, BaseException):
                raise result
            raise kv.KVError(f"storage error: {result!r}")
        if status == _STATUS_OK_TRACED:
            result, remote_span = result
            trace.attach_remote(remote_span)
        return result

    def call_stream(self, method: str, args: tuple, kwargs: dict,
                    credit: int):
        """Generator over a multi-frame streamed reply. Grants one
        credit back per consumed frame (sliding window): the server
        never has more than `credit` frames un-consumed in flight."""
        from tidb_tpu import trace
        cmd = wire.CMD_BY_METHOD.get(method)
        if cmd is None:
            raise kv.KVError(f"method {method!r} has no wire command")
        req = (int(cmd), tuple(args), dict(kwargs, credit=credit))
        if trace.active():
            req = req + (self._trace_flags(trace),)
        _send_frame(self.sock, wire.STATUS_OK, wire.encode(req))
        reader = wire.StreamReader(credit)
        while True:
            status, body = _recv_frame(self.sock)
            kind, frame = reader.feed(status, body)
            if kind == "end":
                if isinstance(frame, dict):
                    # the server's span tree rode the END frame
                    trace.attach_remote(frame)
                return
            yield frame
            # consumer is done with that frame: open the window one slot
            reader.grant(1)
            _send_frame(self.sock, wire.STATUS_CREDIT, wire.encode(1))

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


class RemoteClient:
    """Connection pool + failure translation + replica failover (ref:
    client.go connArray + region_request.go onSendFail; the failover
    orchestration the reference delegates to PD lives here, client-side
    — documented single-host substitute).

    `addr` may be one (host, port) or a list of them: a primary plus its
    backup. On dial failure the client rotates to the next address; when
    it reaches a node answering NotLeader(leader_store=-1) (the backup
    sentinel) while the old primary is unreachable, it promotes that
    backup and retries."""

    def __init__(self, addr, max_conns: int = MAX_CONNS,
                 retry_window: float = 10.0):
        if isinstance(addr, list):
            self.addrs = list(addr)
        else:
            self.addrs = [addr]
        self.retry_window = retry_window
        self._cur = 0                      # index of believed primary
        self._pools: dict = {}             # addr -> list[_Conn]
        self._sema = threading.Semaphore(max_conns)
        self._mu = threading.Lock()
        # fired once per observed connection-level failure (dial or
        # mid-request I/O): fleet-mode storages drop stale region
        # epochs here so a reconnect re-resolves routing instead of
        # looping on interrupted streams
        self._disconnect_listeners: list = []

    def add_disconnect_listener(self, fn) -> None:
        self._disconnect_listeners.append(fn)

    def _notify_disconnect(self) -> None:
        for fn in list(self._disconnect_listeners):
            try:
                fn()
            except Exception:   # noqa: BLE001 — listeners are best-effort
                pass

    @property
    def addr(self):
        return self.addrs[self._cur]

    def _checkout(self) -> tuple:
        with self._mu:
            addr = self.addrs[self._cur]
            pool = self._pools.get(addr)
            if pool:
                return addr, pool.pop()
        return addr, _Conn(addr)

    def _checkin(self, addr, conn: _Conn) -> None:
        with self._mu:
            if addr == self.addrs[self._cur]:
                pool = self._pools.setdefault(addr, [])
                if len(pool) < MAX_CONNS:
                    pool.append(conn)
                    return
        conn.close()

    def _rotate(self, from_addr) -> None:
        with self._mu:
            if self.addrs[self._cur] == from_addr and len(self.addrs) > 1:
                self._cur = (self._cur + 1) % len(self.addrs)

    def _old_primary_unreachable(self, backup_addr) -> bool:
        for a in self.addrs:
            if a == backup_addr:
                continue
            try:
                c = _Conn(a, timeout=1.0)
            except OSError:
                continue
            try:
                if c.call("repl_hello", (), {}).get("role") == "primary":
                    return False
            except Exception:   # noqa: BLE001 — unhealthy counts as dead
                pass
            finally:
                c.close()
        return True

    def _promote(self, addr) -> None:
        c = _Conn(addr)
        try:
            c.call("repl_promote", (), {})
        finally:
            c.close()

    def call(self, method: str, *args, **kwargs):
        self._sema.acquire()
        try:
            return self._call_inner(method, args, kwargs)
        finally:
            self._sema.release()

    def _call_inner(self, method: str, args, kwargs):
        deadline = time.monotonic() + self.retry_window
        idempotent = method in _IDEMPOTENT
        while True:
            try:
                addr, conn = self._checkout()
            except OSError as e:
                self._notify_disconnect()
                self._rotate(self.addrs[self._cur])
                if time.monotonic() < deadline:
                    time.sleep(0.1)
                    continue    # storage may be restarting: keep dialing
                raise kv.ServerBusyError(
                    f"storage unreachable at {self.addr}: {e}") from None
            t0 = time.monotonic()
            try:
                result = conn.call(method, args, kwargs)
            except kv.NotLeaderError as e:
                conn.close()
                if e.leader_store == -1:
                    # reached a backup: promote it iff the primary is
                    # really gone, else go back to the primary
                    if self._old_primary_unreachable(addr):
                        try:
                            self._promote(addr)
                        except (ConnectionError, OSError) as pe:
                            raise kv.ServerBusyError(
                                f"failover promote failed: {pe}") from None
                        continue
                    self._rotate(addr)
                    continue
                raise
            except (ConnectionError, OSError, wire.WireError,
                    EOFError) as e:
                conn.close()
                self._notify_disconnect()
                self._rotate(addr)
                if idempotent and time.monotonic() < deadline:
                    time.sleep(0.05)
                    continue
                if idempotent:
                    raise kv.ServerBusyError(
                        f"storage i/o failure: {e}") from None
                # a mutating command may or may not have executed
                raise TimeoutError_(
                    f"storage i/o failure mid-request: {e}") from None
            from tidb_tpu import metrics
            metrics.histogram(metrics.FLEET_RPC_SECONDS,
                              time.monotonic() - t0, {"method": method})
            self._checkin(addr, conn)
            return result

    def call_stream(self, method: str, *args, credit: int = 4, **kwargs):
        """Streamed call: yields frames as the server ships them. Any
        network/protocol failure surfaces as kv.StreamInterruptedError —
        the coprocessor client resumes from its last acked range
        boundary (store/copr.py), so no transparent re-send happens
        here (a blind replay could duplicate already-consumed frames).
        The connection returns to the pool only after a CLEAN end (or a
        typed error frame, which leaves framing intact); an abandoned or
        broken stream closes it."""
        # the sysvar is unbounded; the wire protocol is not — clamp
        # rather than spin a legal SET value through the retry budget
        credit = max(1, min(credit, wire.MAX_STREAM_CREDIT))
        self._sema.acquire()
        conn = None
        clean = False
        try:
            try:
                addr, conn = self._checkout()
            except OSError as e:
                self._notify_disconnect()
                self._rotate(self.addrs[self._cur])
                raise kv.StreamInterruptedError(
                    f"storage unreachable at {self.addr}: {e}") from None
            consumed = 0
            try:
                for frame in conn.call_stream(method, args, kwargs,
                                              credit):
                    consumed += 1
                    yield frame
                clean = True
            except kv.NotLeaderError as e:
                # typed error frame. Framing is intact ONLY if no frame
                # was consumed yet (no grants in flight the server
                # cannot account for); else both ends drop the conn.
                clean = consumed == 0
                if e.leader_store == -1 and \
                        self._old_primary_unreachable(addr):
                    # reached a backup with the primary gone: promote,
                    # then let the caller's resume loop retry against it
                    try:
                        self._promote(addr)
                    except (ConnectionError, OSError) as pe:
                        raise kv.ServerBusyError(
                            f"failover promote failed: {pe}") from None
                    raise kv.StreamInterruptedError(
                        "backup promoted; resume stream") from None
                if e.leader_store == -1:
                    self._rotate(addr)
                raise
            except kv.KVError:
                clean = consumed == 0   # see NotLeaderError note above
                raise
            except (ConnectionError, OSError, wire.WireError,
                    EOFError) as e:
                self._notify_disconnect()
                self._rotate(addr)
                raise kv.StreamInterruptedError(
                    f"stream i/o failure: {e}") from None
        finally:
            if conn is not None:
                if clean:
                    self._checkin(addr, conn)
                else:
                    conn.close()
            self._sema.release()

    def close(self) -> None:
        with self._mu:
            for pool in self._pools.values():
                for c in pool:
                    c.close()
            self._pools.clear()


class _RemotePD:
    """Cluster-lookalike for RegionCache + PDOracle: region routing and
    TSO served by the storage process (the PD role)."""

    def __init__(self, client: RemoteClient):
        self.client = client

    def region_by_key(self, key: bytes):
        return self.client.call("region_by_key", key)

    def tso(self) -> int:
        return self.client.call("tso")

    def all_regions(self):
        return self.client.call("regions_snapshot")

    # test/benchmark topology control
    def split(self, key: bytes):
        return self.client.call("split", key)

    def split_table(self, table_id: int, count: int,
                    max_handle: int = 1 << 20):
        return self.client.call("split_table", table_id, count,
                                max_handle=max_handle)


class _RemoteShim:
    """RPCShim-lookalike: every kv_*/coprocessor call rides the wire."""

    def __init__(self, client: RemoteClient):
        self.client = client

    def __getattr__(self, name: str):
        if name.startswith(("kv_", "raw_", "mvcc_")) or \
                name in ("coprocessor", "split_region", "journal_window"):
            def call(*args, **kwargs):
                return self.client.call(name, *args, **kwargs)
            return call
        raise AttributeError(name)

    def coprocessor_stream(self, ctx, req, credit=None, frame_bytes=None):
        """Streamed coprocessor over the wire: lazy frame generator
        under the credit window (see StorageServer._serve_stream). The
        client's frame cap ships with the request — the storage
        process's own sysvar must not override this session's memory
        bound."""
        kwargs = {}
        if frame_bytes is not None:
            kwargs["frame_bytes"] = frame_bytes
        return self.client.call_stream("coprocessor_stream", ctx, req,
                                       credit=credit or 4, **kwargs)


class _FleetShim(_RemoteShim):
    """Fleet-mode shim: coprocessor tasks are first offered to this
    SQL-server process's OWN cache hierarchy (store/fleetcop.py — a
    journal-window pull primes the serve), and fall through to the
    store plane when not locally servable. Everything else rides the
    wire unchanged."""

    def __init__(self, client: RemoteClient, storage):
        super().__init__(client)
        self._storage = storage

    def coprocessor(self, ctx, req):
        from tidb_tpu import metrics
        from tidb_tpu.store import fleetcop
        res = fleetcop.exec_local(self._storage, self, ctx, req)
        if res is not None:
            return res[0]
        metrics.counter(metrics.FLEET_LOCAL_COP, {"path": "store"})
        return self.client.call("coprocessor", ctx, req)

    def coprocessor_stream(self, ctx, req, credit=None, frame_bytes=None):
        """Streamed flavor of the local-first offer: a locally served
        task ships as ONE synthesized final frame (the cached block is
        already resident — framing it would only re-buffer it), with
        `range` covering the clamped task range so the client's cursor
        and cross-region continuation work unchanged. The offer runs
        lazily on first next(), inside the cop client's per-frame retry
        scope, so region errors from the journal-window pull re-locate
        exactly like mid-stream region errors."""
        def frames():
            from tidb_tpu import metrics
            from tidb_tpu.store import fleetcop
            from tidb_tpu.store.stream import StreamFrame
            res = fleetcop.exec_local(self._storage, self, ctx, req)
            if res is None:
                metrics.counter(metrics.FLEET_LOCAL_COP,
                                {"path": "store"})
                yield from _RemoteShim.coprocessor_stream(
                    self, ctx, req, credit=credit,
                    frame_bytes=frame_bytes)
                return
            out, s, e = res
            rng = kv.KVRange(s, e)
            if not out:
                yield StreamFrame(chunk=None, range=rng, last=True)
                return
            for i, resp in enumerate(out):
                yield StreamFrame(chunk=resp.chunk, range=rng,
                                  last=i == len(out) - 1)
        return frames()


class _RemoteEngine:
    """Offline-import surface of the remote engine (bulkload)."""

    def __init__(self, client: RemoteClient):
        self.client = client

    def bulk_import(self, pairs, start_ts: int, commit_ts: int) -> int:
        return self.client.call("bulk_import", list(pairs), start_ts,
                                commit_ts)


class RemoteStorage(kv.Storage):
    """kv.Storage whose shim/PD/TSO live in another process. Drop-in for
    MockStorage at the session layer: txns, snapshots, coprocessor
    fan-out, GC all run their existing client logic over the wire."""

    def __init__(self, addr, local_cache: bool = False):
        from tidb_tpu.store.oracle import PDOracle
        from tidb_tpu.store.region_cache import RegionCache
        from tidb_tpu.store.txn import KVTxn, LockResolver, TxnSnapshot
        self._txn_cls = KVTxn
        self._snap_cls = TxnSnapshot
        self.rpc = RemoteClient(addr)
        self.pd = _RemotePD(self.rpc)
        self.cluster = self.pd              # topology ops for tests/bench
        self.engine = _RemoteEngine(self.rpc)
        self.region_cache = RegionCache(self.pd)
        if local_cache:
            # fleet mode: this SQL server keeps its own columnar chunk
            # cache + HBM device cache, kept coherent with the store
            # plane by journal-window pulls (store/fleetcop.py)
            from tidb_tpu.store.chunk_cache import ChunkCache
            from tidb_tpu.store.device_cache import DeviceCache
            self.chunk_cache = ChunkCache()
            self.device_cache = DeviceCache()
            self.shim = _FleetShim(self.rpc, self)
            # a dropped store connection invalidates every cached
            # region epoch: the reconnected plane may have split/moved
            # regions while we were gone, and resuming with stale
            # routing loops on interrupted streams
            self.rpc.add_disconnect_listener(
                self.region_cache.invalidate_all)
        else:
            self.shim = _RemoteShim(self.rpc)
        self.oracle = PDOracle(self.pd)
        self.resolver = LockResolver(self.shim, self.region_cache,
                                     self.oracle)
        self.async_commit_secondaries = True
        self._client = None
        self.safepoint = 0

    def begin(self, start_ts: int | None = None):
        return self._txn_cls(self, start_ts if start_ts is not None
                             else self.oracle.get_timestamp())

    def snapshot(self, ts: int):
        return self._snap_cls(self.shim, self.region_cache, self.resolver,
                              ts, storage=self)

    def current_ts(self) -> int:
        return self.oracle.get_timestamp()

    def check_visibility(self, ts: int) -> None:
        if ts < self.safepoint:
            raise kv.GCTooEarlyError(
                f"snapshot ts {ts} is below GC safepoint {self.safepoint}")

    def update_safepoint(self, sp: int) -> None:
        self.safepoint = max(self.safepoint, sp)

    def client(self):
        if self._client is None:
            from tidb_tpu.store.copr import CopClient
            self._client = CopClient(self)
        return self._client

    def ping(self) -> bool:
        return self.rpc.call("ping") == "pong"

    def close(self) -> None:
        self.oracle.close()
        dc = getattr(self, "device_cache", None)
        if dc is not None:
            dc.shed()   # return the HBM ledger share eagerly
        self.rpc.close()


def connect(host: str, port: int, *backups,
            local_cache: bool = False) -> RemoteStorage:
    """backups: extra (host, port) pairs forming the replica set.
    local_cache=True enables fleet mode (per-process coherent caches)."""
    addrs = [(host, port)] + [tuple(b) for b in backups]
    return RemoteStorage(addrs if len(addrs) > 1 else addrs[0],
                         local_cache=local_cache)


# ---------------------------------------------------------------------------
# process entry: python -m tidb_tpu.store.remote --port N

def serve_main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tidb_tpu.store.remote",
                                description="storage node process")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--status-port", type=int, default=0)
    p.add_argument("--no-status", action="store_true",
                   help="disable the HTTP status server (and with it "
                        "this node's fleet membership registration)")
    p.add_argument("--snapshot", default=None,
                   help="state snapshot file (loaded at start, saved on "
                        "graceful shutdown)")
    p.add_argument("--role", choices=["primary", "backup"],
                   default="primary")
    p.add_argument("--backup", default=None, metavar="HOST:PORT",
                   help="(primary) ship every mutation here synchronously")
    p.add_argument("--primary", default=None, metavar="HOST:PORT",
                   help="(backup) pull initial state from this primary")
    p.add_argument("--retain-ms", type=int, default=None,
                   help="delta-journal retention window in ms "
                        "(tidb_tpu_delta_retain_ms): keep this much "
                        "journal behind now so fleet SQL servers can "
                        "pull coherence windows")

    def _addr(s):
        h, _, pt = s.rpartition(":")
        return (h or "127.0.0.1", int(pt))

    args = p.parse_args(argv)
    if args.retain_ms is not None:
        from tidb_tpu import config
        config.set_var("tidb_tpu_delta_retain_ms", args.retain_ms)
    server = StorageServer(
        args.host, args.port, snapshot_path=args.snapshot,
        role=args.role,
        backup_addr=_addr(args.backup) if args.backup else None,
        primary_addr=_addr(args.primary) if args.primary else None)
    server.start()
    print(f"storage listening on {args.host}:{server.port}", flush=True)
    status = None
    if not args.no_status:
        # the store plane is a first-class fleet member: it serves the
        # same status surface (metrics, traces, /cluster/state) and
        # registers in the membership registry it hosts, so any SQL
        # member's cluster_* queries include store-plane rows — and the
        # store-retained traces become reachable fleet-wide
        from tidb_tpu import member
        from tidb_tpu.server.status import StatusServer
        status = StatusServer(server.storage, None, host=args.host,
                              port=args.status_port)
        status.start()
        member.set_identity(args.host, status.port, "store")
        member.start_heartbeat(server.storage)
        print(f"status API on {args.host}:{status.port}", flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    if status is not None:
        from tidb_tpu import member
        member.stop_heartbeat()
        status.close()
    server.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(serve_main())
