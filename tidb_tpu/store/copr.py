"""Coprocessor: pushed-subplan execution near the data + client fan-out.

Reference: /root/reference/store/tikv/coprocessor.go (client: buildCopTasks
:263, worker pool :342-457, per-task retry :574-605) and
mocktikv/cop_handler_dag.go:46-107 (storage side: decode DAG, run the
executor chain over the region's data). Storage-side compute here is the
TPU operator library (ops/) — the "analytical path runs as XLA kernels next
to the data"; host numpy is the fallback for non-device-safe plans.
"""

from __future__ import annotations

import queue
import threading
from collections import OrderedDict
from concurrent.futures import ThreadPoolExecutor

from tidb_tpu import (config, devplane, kv, memtrack, meter, profiler,
                      runtime_stats, sched, tablecodec, trace)
from tidb_tpu.kv import (CopRequest, CopResponse, KVRange, NotLeaderError,
                         RegionError, ReqType, ServerBusyError,
                         KeyLockedError)
from tidb_tpu.mockstore.cluster import Region
from tidb_tpu.ops.hashagg import (CapacityError, CollisionError,
                                  DeviceRejectError)
from tidb_tpu.ops.hostagg import host_hash_agg, host_scalar_agg
from tidb_tpu.ops.runtime import bucket_size, eval_filter_host
from tidb_tpu.plan.physical import CopPlan
from tidb_tpu.store.backoff import (BO_REGION_MISS, BO_RPC,
                                    BO_SERVER_BUSY, BO_TXN_LOCK,
                                    BackoffExhausted, Backoffer,
                                    COP_MAX_BACKOFF)
from tidb_tpu.table import index_kvrows_to_chunk, kvrows_to_chunk
from tidb_tpu.util import failpoint
from tidb_tpu.util.failpoint import DeviceFaultError

__all__ = ["CopClient", "cop_handler", "decode_cop_batch",
           "exec_cop_plan", "exec_cached_cop", "use_cached_path"]

# fan-out width lives in the tidb_tpu_cop_concurrency sysvar (config.py;
# ref: DistSQLScanConcurrency default, sessionctx/variable/tidb_vars.go:115)

# storage-side scan batching; large batches amortize device dispatch
COP_SCAN_BATCH = 65536

_kernel_lock = threading.Lock()
_memo_lock = threading.Lock()


def _plan_filter_memoizable(plan: CopPlan) -> bool:
    """A filter result may be memoized only when its predicates hold no
    correlated cells — ApplyExec rebinds those per outer row while
    reusing the SAME plan object, so a memo would freeze row 1's answer.
    Computed once and cached on the plan."""
    cached = getattr(plan, "_filter_memoizable", None)
    if cached is not None:
        return cached
    from tidb_tpu.expression.core import CorrelatedCol, ScalarFunc

    def correlated(e) -> bool:
        if e is None:
            return False
        if isinstance(e, CorrelatedCol):
            return True
        if isinstance(e, ScalarFunc):
            return any(correlated(a) for a in e.args)
        return False

    ok = not correlated(plan.filter) and not correlated(plan.host_filter)
    plan._filter_memoizable = ok
    return ok


def _agg_kernels(plan: CopPlan):
    """Compiled kernel cached on the plan object (one jit program per
    pushed subplan, reused across regions and chunks), resolved through
    the process-wide fingerprint cache so a re-created plan (plan-cache
    miss, new session) reuses the traced program instead of re-tracing."""
    from tidb_tpu.ops.hashagg import kernel_for
    with _kernel_lock:
        k = getattr(plan, "_kernel", None)
        if k is None:
            k = kernel_for(plan.filter, plan.group_exprs, plan.aggs)
            plan._kernel = k
    return k


def decode_cop_batch(plan: CopPlan, batch):
    """Raw (key, value) rows -> decoded chunk for `plan` (row or index
    encoding). Shared by the materialized handler below and the framed
    producer in store/stream.py."""
    if plan.index is not None:
        return index_kvrows_to_chunk(plan.table, plan.index, plan.cols,
                                     batch, handle_col=plan.handle_col)
    return kvrows_to_chunk(plan.table, plan.cols, batch,
                           with_handle_col=plan.handle_col)


def _resolve_block(plan: CopPlan, chunk, dev_ref):
    """The HBM-resident DeviceBlock for this chunk, or None. Shared by
    the decoded and the encoded-filter dispatch paths."""
    if dev_ref is None or not config.fused_scan_enabled():
        return None
    dcache, dkey, dv, read_ts, fill_ts, pend_fn = dev_ref
    block = dcache.get_or_fill(dkey, dv, read_ts, chunk, fill_ts,
                               pend_fn=pend_fn)
    if block is not None and block.nrows == chunk.num_rows:
        return block
    return None


def _agg_mode(plan: CopPlan, k) -> str:
    """The encoding-mode note for a successful device agg dispatch —
    derived from the kernel ACTUALLY selected: one degraded past
    tidb_tpu_direct_agg_slots (force_hash) must not keep reporting
    direct-agg, or the note hides exactly the regression it exists to
    diagnose."""
    from tidb_tpu.ops.hashagg import _direct_group_mode
    return "direct-agg" if plan.group_exprs and \
        not getattr(k, "force_hash", False) and \
        _direct_group_mode(plan.group_exprs) else "encoded"


def _encoded_agg(plan: CopPlan, chunk, sources: int,
                 dev_ref) -> CopResponse | None:
    """Device partial agg with the host-only string filter translated
    into CODE space (ops/encoded.py): the chunk's dict columns are
    compared against pre-encoded constant codes inside the kernel, so
    the fused HBM dispatch stays available and the host never rewrites
    the chunk. Returns None to run the decoded path instead — counted
    as tidb_tpu_device_fallback_total{reason="encoding"} when the
    filter is not encodable (a capacity/collision miss returns None
    silently: the decoded retry owns that bookkeeping, and the encoded
    filter must never reach a host evaluator)."""
    from tidb_tpu.expression.core import Op, func
    from tidb_tpu.ops import encoded
    from tidb_tpu.ops.hashagg import kernel_for
    # translatability gate BEFORE touching the device cache: an
    # untranslatable filter must not fill HBM with blocks this query
    # can never consume (vocabulary support doesn't depend on which
    # dictionary the constants encode against)
    enc = encoded.translate_filter(plan.host_filter, chunk)
    if enc is None:
        runtime_stats.note_fallback(plan, "encoding")
        return None
    block = _resolve_block(plan, chunk, dev_ref)
    if block is not None:
        # re-encode the constants against the dictionaries the resident
        # code lanes were actually built with — delta patches extend
        # them past the chunk's own memoized encode
        enc = encoded.translate_filter(
            plan.host_filter, chunk,
            dict_of=lambda j, _b=block: _b.dicts.get(j))
        if enc is None:     # block lost a dictionary: decoded path
            runtime_stats.note_fallback(plan, "encoding")
            return None
    eff = enc if plan.filter is None else func(Op.AND, plan.filter, enc)
    try:
        k = kernel_for(eff, plan.group_exprs or [], plan.aggs)
    except (DeviceRejectError, NotImplementedError, ValueError):
        runtime_stats.note_fallback(plan, "encoding")
        return None
    try:
        if block is not None:
            dev_cols, nbytes = block.cols, k.scratch_nbytes(chunk)
            moved = block.nbytes
        else:
            dev_cols = None
            moved = memtrack.device_put_bytes(chunk)
            nbytes = k.dispatch_nbytes(chunk)
        failpoint.eval("device/dispatch")
        with sched.device_slot() as slot, \
                devplane.chip_scope(slot.chip), \
                memtrack.device_scope(plan, nbytes):
            # split spans on the sync path too: the async enqueue
            # (pad/transfer/jit dispatch) vs the blocking readback —
            # the same per-superchunk pair the pipelined paths record.
            # Device timing covers BOTH halves, success-only — exactly
            # the interval device_call used to measure here (the
            # kernel-profile section shares the success-only contract:
            # a capacity miss's wall must not bill the profile row the
            # decoded retry will bill again)
            with runtime_stats.device_section(plan, errors=False), \
                    profiler.dispatch_section(
                        profiler.profile_of(k), nbytes=nbytes,
                        encoded=moved,
                        decoded=memtrack.chunk_bytes(chunk), plan=plan):
                with trace.span("dispatch", rows=chunk.num_rows,
                                chip=slot.chip):
                    pending = k.dispatch(chunk, dev_cols=dev_cols)
                failpoint.eval("device/finalize")
                with trace.span("finalize"):
                    res = k.finalize(chunk, pending)
        sched.device_health().note_ok()
    except failpoint.DispatchTimeoutError:
        raise       # statement already cancel-latched by the watchdog
    except DeviceFaultError:
        # device-plane fault: the decoded retry below owns the
        # retry/degrade bookkeeping — just record the fault here
        sched.device_health().note_fault()
        return None
    except (CapacityError, CollisionError, DeviceRejectError,
            NotImplementedError):
        # the decoded retry re-runs with the ORIGINAL filter tree (the
        # code-space one is device-only) and records its own outcome
        return None
    mode = _agg_mode(plan, k)
    runtime_stats.note_encoding(plan, mode)
    runtime_stats.note_mode(
        plan, "direct" if mode == "direct-agg" else "hash")
    runtime_stats.note_bytes_touched(memtrack.chunk_bytes(chunk), moved)
    if config.superchunk_rows():
        runtime_stats.note_superchunk(
            plan, chunk.num_rows, bucket_size(max(chunk.num_rows, 1)),
            sources)
    return CopResponse(chunk=res)


def exec_cop_plan(plan: CopPlan, chunk, sources: int = 1,
                  dev_ref=None) -> CopResponse:
    """Run the pushed subplan over one region's decoded chunk.
    `sources` is how many storage scan batches were coalesced into
    `chunk` (superchunk accounting for EXPLAIN ANALYZE / metrics).

    `dev_ref` — a (device_cache, key, data_version, read_ts, fill_ts,
    pend_fn) tuple from _cached_range_chunk — marks `chunk` as an
    HBM-cacheable region block: a device agg dispatch then runs FUSED
    from the cached device-resident columns (scan->filter->partial-agg
    in one compiled call, zero host->device bytes on a hit). fill_ts
    None = consult only, never fill (the MVCC fill conditions did not
    hold); pend_fn lets the HBM cache fold staged row deltas into the
    resident block in place (store/delta.py)."""
    # one health-gate evaluation per call, shared by the encoded and
    # decoded device attempts: the quarantine probe admission is a
    # consumable token, and the fault/quarantine fallback must count
    # once per logical dispatch, not once per attempted path
    health_ok = None

    def _health_gate() -> bool:
        nonlocal health_ok
        if health_ok is None:
            if sched.statement_degraded():
                # a retried device fault already latched this
                # statement onto the host path
                runtime_stats.note_fallback(plan, "fault")
                health_ok = False
            elif not sched.device_health().available():
                # device quarantined after repeated faults; the host
                # path serves until the re-probe readmits it
                runtime_stats.note_fallback(plan, "quarantine")
                health_ok = False
            else:
                health_ok = True
        return health_ok

    if plan.host_filter is not None:
        if (plan.is_agg and config.encoded_exec_enabled() and
                config.device_enabled() and
                chunk.num_rows >= config.device_min_rows() and
                _health_gate()):
            resp = _encoded_agg(plan, chunk, sources, dev_ref)
            if resp is not None:
                return resp
        # decoded path: the host filter rewrites the chunk, so the raw
        # cached block no longer matches it — the fused path only
        # covers device-complete (or code-translated) predicates
        dev_ref = None
        mask = eval_filter_host(plan.host_filter, chunk)
        chunk = chunk.filter(mask)
        if plan.is_agg:
            runtime_stats.note_encoding(plan, "decoded")
    if plan.is_agg:
        use_device = (config.device_enabled() and
                      chunk.num_rows >= config.device_min_rows() and
                      _health_gate())
        retried = False
        while use_device:
            try:
                k = _agg_kernels(plan)
                dev_cols = None
                block = _resolve_block(plan, chunk, dev_ref)
                if block is not None:
                    # the input columns stay on the cache's own
                    # ledger; the statement pays only kernel scratch
                    dev_cols = block.cols
                    nbytes = k.scratch_nbytes(chunk)
                    moved = block.nbytes
                else:
                    moved = memtrack.device_put_bytes(chunk)
                    nbytes = k.dispatch_nbytes(chunk)
                # device ledger: padded upload + scratch, sized from
                # shapes at dispatch; the pool worker's tracker routes
                # the charge to the issuing reader's node. The dispatch
                # slot puts storage-side aggs under the same global
                # round-robin window as executor-side kernels
                failpoint.eval("device/dispatch")
                with sched.device_slot() as slot, \
                        devplane.chip_scope(slot.chip), \
                        memtrack.device_scope(plan, nbytes), \
                        runtime_stats.device_section(plan,
                                                     errors=False), \
                        profiler.dispatch_section(
                            profiler.profile_of(k), nbytes=nbytes,
                            encoded=moved,
                            decoded=memtrack.chunk_bytes(chunk),
                            plan=plan):
                    with trace.span("dispatch", rows=chunk.num_rows,
                                    chip=slot.chip):
                        pending = k.dispatch(chunk, dev_cols=dev_cols)
                    # the sync path's "blocking readback" seam: inside
                    # the watchdog-guarded slot, so an armed delay here
                    # exercises the timeout -> retryable-cancel path
                    failpoint.eval("device/finalize")
                    with trace.span("finalize"):
                        res = k.finalize(chunk, pending)
                sched.device_health().note_ok()
                if plan.host_filter is None:
                    runtime_stats.note_encoding(plan, _agg_mode(plan, k))
                runtime_stats.note_mode(
                    plan, "direct" if _agg_mode(plan, k) == "direct-agg"
                    else "hash")
                runtime_stats.note_bytes_touched(
                    memtrack.chunk_bytes(chunk), moved)
                if config.superchunk_rows():
                    # attribution follows the feature switch: with
                    # coalescing off this is plain per-batch dispatch,
                    # not superchunk execution
                    runtime_stats.note_superchunk(
                        plan, chunk.num_rows,
                        bucket_size(max(chunk.num_rows, 1)), sources)
                return CopResponse(chunk=res)
            except failpoint.DispatchTimeoutError:
                # the watchdog already cancel-latched the statement:
                # retrying is futile, the cancel must surface
                raise
            except DeviceFaultError as e:
                # device-plane fault (injected or real — HBM fill,
                # dispatch transport): retry ONCE through the store
                # Backoffer, then degrade this statement to the host
                # path and let the quarantine logic decide whether the
                # device keeps taking other statements' work
                sched.device_health().note_fault()
                if not retried:
                    retried = True
                    trace.event("device.retry")
                    try:
                        Backoffer(2_000).backoff(BO_RPC, e)
                    except BackoffExhausted:
                        pass
                    continue
                sched.degrade_statement()
                runtime_stats.note_fallback(plan, "fault")
                profiler.note_kernel_fallback(profiler.profile_of(k),
                                              "fault")
                break
            except (CapacityError, CollisionError) as e:
                if plan.group_exprs:
                    # capacity/collision miss: escalate once, then retry
                    # per radix partition (ops/hybrid.py) — the device
                    # is abandoned per PARTITION, never per operator
                    from tidb_tpu.ops.hybrid import agg_retry
                    profiler.note_escalation(profiler.profile_of(k))
                    runtime_stats.note_mode(plan, "hybrid")
                    return CopResponse(chunk=agg_retry(
                        chunk, plan.filter, plan.group_exprs, plan.aggs,
                        plan, e))
                reason = "collision" if isinstance(e, CollisionError) \
                    else "capacity"
                runtime_stats.note_fallback(plan, reason)
                profiler.note_kernel_fallback(profiler.profile_of(k),
                                              reason)
                break
            except (DeviceRejectError, NotImplementedError):
                # designed rejection (not device-safe). A bare
                # ValueError is NOT caught here any more: a real kernel
                # bug must surface, not masquerade as a capacity miss
                runtime_stats.note_fallback(plan, "unsupported")
                break
        runtime_stats.note_encoding(plan, "decoded")
        runtime_stats.note_mode(plan, "host")
        # host-path agg time is its own attribution phase: with the
        # device degraded/quarantined (or plain host mode) THIS is
        # where the statement's microseconds go — on the trace AND on
        # the tenant's host-fallback ledger (meter.py)
        with meter.busy_section("host"), \
                trace.span("host.fallback", rows=chunk.num_rows):
            if plan.group_exprs:
                return CopResponse(chunk=host_hash_agg(
                    chunk, plan.filter, plan.group_exprs, plan.aggs))
            return CopResponse(chunk=host_scalar_agg(
                chunk, plan.filter, plan.aggs))
    if plan.filter is not None:
        mask = eval_filter_host(plan.filter, chunk)
        chunk = chunk.filter(mask)
    return CopResponse(chunk=chunk)


def _delta_store_of(storage):
    """The storage's delta store when capture is active, else None."""
    dstore = getattr(storage, "delta_store", None)
    if dstore is None or not dstore.enabled():
        return None
    return dstore


def _dev_pending_fn(dstore, plan: CopPlan, s: bytes, e: bytes):
    """Closure the HBM cache calls to fetch (and plan-layout decode)
    the staged delta window for ITS entry's fill_ts — the device block
    may lag or lead the host entry, so the window is per-consumer."""
    from tidb_tpu.store import delta as deltamod

    def pend_fn(lo_ts: int, hi_ts: int):
        pend = dstore.pending(plan.table.id, s, e, lo_ts, hi_ts)
        if pend is None or pend is deltamod.STALE:
            return pend
        if pend.decoded is None:
            pend.decoded = decode_cop_batch(plan, pend.upsert_rows)
        return pend

    return pend_fn


def _cached_range_chunk(storage, region: Region, plan: CopPlan, s: bytes,
                        e: bytes, req: CopRequest):
    """Whole-range decoded chunk with host-cache lookup/fill, served as
    base ⋈ delta under OLTP writes (store/delta.py).
    -> (chunk, dev_ref): dev_ref parameterizes the HBM device cache
    (store/device_cache.py) for a fused dispatch over the same block —
    (cache, key, data_version, read_ts, fill_ts, pend_fn), with fill_ts
    None when the MVCC fill conditions did not hold (consult-only) and
    fill_ts the DELTA WATERMARK when the served chunk is a base⋈delta
    merge."""
    from tidb_tpu.store import delta as deltamod
    from tidb_tpu.store.chunk_cache import ChunkCache
    cache = storage.chunk_cache
    key = ChunkCache.key(region, plan, s, e)
    # resolve the delta store BEFORE sampling the version: the consult
    # has a side effect — flipping tidb_tpu_delta_store off flushes the
    # staged journal and bumps data_version once (DeltaStore.enabled),
    # and sampling first would serve the pre-flush base at the old
    # version
    dstore = _delta_store_of(storage)
    # sample the version BEFORE scanning: a structural write landing
    # mid-scan bumps past it, so the filled entry can never serve stale
    # data (row commits landing mid-scan get commit_ts > start_ts and
    # ride the delta journal instead). A pending lock anywhere also
    # vetoes caching: lock visibility is per-reader-ts, so a fill that
    # legally skipped a newer txn's lock would hide the KeyLockedError
    # a newer reader must hit.
    dv = storage.engine.data_version
    # serve-time lock veto — the delta path's replacement for the
    # prewrite version bump: a pending lock this reader must observe
    # forces the real scan below (which raises KeyLockedError for
    # resolution exactly as an uncached read would) while every cache
    # entry SURVIVES the write
    locked = dstore is not None and \
        storage.engine.locked_in_range(s, e, req.start_ts)
    cacheable = not storage.engine._locked_keys
    fill_ts = None
    hit = None if locked else cache.lookup(key, dv, req.start_ts)
    if hit is not None and dstore is not None:
        if plan.index is not None:
            # index layouts can't be patched from row deltas: an
            # index-key commit since the fill drops the entry (both
            # tiers) so it re-fills at a newer snapshot — other tables
            # and record scans stay untouched
            if dstore.index_stale(plan.table.id, hit[0], req.start_ts):
                cache.drop(key, if_chunk=hit[1])
                dc0 = getattr(storage, "device_cache", None)
                if dc0 is not None:
                    from tidb_tpu.store.device_cache import DeviceCache
                    dc0.drop(DeviceCache.key(region, plan, s, e))
                hit = None
        else:
            pend = dstore.pending(plan.table.id, s, e, hit[0],
                                  req.start_ts)
            if pend is deltamod.STALE:
                # journal truncated under the entry: re-scan
                cache.drop(key, if_chunk=hit[1])
                hit = None
            elif pend is not None:
                with trace.span("delta.fold", rows=hit[1].num_rows):
                    merged = dstore.patch_chunk(cache, key, plan,
                                                hit[1], pend)
                if merged is None:
                    cache.drop(key, if_chunk=hit[1])
                    hit = None
                else:
                    from tidb_tpu import metrics
                    metrics.counter(metrics.CACHE_DELTA_SERVES)
                    hit = (pend.watermark, merged)
    if hit is not None:
        # the host entry's OWN fill snapshot (or delta watermark)
        # bounds the device entry: both caches share one validity
        # window per the freshness contract
        fill_ts, chunk = hit
    else:
        parts = []
        hparts = []
        want_handles = dstore is not None and plan.index is None
        cur = s
        while True:
            batch = storage.engine.scan(cur, e, COP_SCAN_BATCH,
                                        req.start_ts, req.isolation,
                                        desc=False)
            if not batch:
                break
            parts.append(decode_cop_batch(plan, batch))
            if want_handles:
                hparts.append(deltamod.record_handles(
                    [k for k, _v in batch]))
            if len(batch) < COP_SCAN_BATCH:
                break
            cur = batch[-1][0] + b"\x00"
        from tidb_tpu.chunk import Chunk
        chunk = Chunk.concat_all(parts) if parts else \
            decode_cop_batch(plan, [])
        if want_handles:
            import numpy as _np
            chunk._scan_handles = _np.concatenate(hparts) if hparts \
                else _np.zeros(0, dtype=_np.int64)
            dstore.note_base_rows(plan.table.id, chunk.num_rows)
        # cache only fills whose snapshot covers every commit: an older
        # snapshot's view is valid for ITS ts but must not become the
        # cached truth for newer readers (see MVCCStore.max_commit_ts)
        if cacheable and req.start_ts >= storage.engine.max_commit_ts:
            fill_ts = req.start_ts
            cache.put(key, dv, fill_ts, chunk)
    dev_ref = None
    dcache = getattr(storage, "device_cache", None)
    if dcache is not None and plan.is_agg and plan.host_filter is None \
            and not locked and dcache.enabled():
        from tidb_tpu.store.device_cache import DeviceCache
        pend_fn = None
        if dstore is not None and plan.index is None:
            pend_fn = _dev_pending_fn(dstore, plan, s, e)
        dev_ref = (dcache, DeviceCache.key(region, plan, s, e), dv,
                   req.start_ts, fill_ts, pend_fn)
    return chunk, dev_ref


def exec_cached_cop(storage, region: Region, plan: CopPlan, s: bytes,
                    e: bytes, req: CopRequest) -> list[CopResponse]:
    """One region task served through the columnar caches: whole-range
    decoded chunk (host chunk cache), HBM-resident block for fused agg
    dispatch (device cache), memoized filter results. Shared by the
    materialized handler and the streaming producer, so COP_STREAM
    reads hit exactly the same cache hierarchy."""
    chunk, dev_ref = _cached_range_chunk(storage, region, plan, s, e, req)
    if chunk.num_rows == 0:
        return []
    if not plan.is_agg and (plan.filter is not None or
                            plan.host_filter is not None) and \
            _plan_filter_memoizable(plan):
        # FILTER-only plans memoize their result on the cached
        # raw chunk: repeated hot scans then return the SAME
        # filtered chunk object, so every downstream device
        # memo (shard transfers, build tables) keeps hitting —
        # re-filtering per execution silently re-uploaded whole
        # probe tables. Agg plans stay uncached so the host and
        # device paths both really compute (the bench contract).
        with _memo_lock:
            memo = getattr(chunk, "_cop_filter_memo", None)
            if memo is None:
                memo = chunk._cop_filter_memo = OrderedDict()
            hit = memo.get(id(plan))
            if hit is not None:
                memo.move_to_end(id(plan))
                return [hit[1]]
        resp = exec_cop_plan(plan, chunk)
        from tidb_tpu.store.chunk_cache import ChunkCache, _chunk_bytes
        with _memo_lock:
            if id(plan) not in memo:
                # entry pins plan, so the id cannot be recycled
                memo[id(plan)] = (plan, resp)
                while len(memo) > 8:
                    memo.popitem(last=False)
                # memoized results count toward the raw entry's
                # cache budget (evicting the raw chunk drops
                # them all)
                storage.chunk_cache.add_cost(
                    ChunkCache.key(region, plan, s, e),
                    _chunk_bytes(resp.chunk))
        return [resp]
    return [exec_cop_plan(plan, chunk, dev_ref=dev_ref)]


def use_cached_path(storage, plan: CopPlan) -> bool:
    """True when a region task is served through the columnar caches
    (whole-range, no LIMIT short-circuit)."""
    return (plan.limit is None and config.chunk_cache_enabled()
            and getattr(storage, "chunk_cache", None) is not None)


def clamp_range(region: Region, rng: KVRange) -> tuple[bytes, bytes]:
    """Clamp one request range to a region's bounds. Cache keys embed
    this (s, e), so the materialized handler and the streaming producer
    (store/stream.py) MUST share this one clamp — diverging copies
    would silently stop their cache entries from serving each other."""
    s = max(rng.start, region.start)
    if region.end and rng.end:
        e = min(rng.end, region.end)
    else:
        e = region.end or rng.end   # either bound may be open (falsy)
    return s, e


def cop_handler(storage):
    """Builds the storage-side handler closure installed into the RPC shim.
    Executes scan+filter+partial-agg for one region (cop_handler_dag.go's
    role). Unlimited scans are served through the storage node's columnar
    chunk cache (store/chunk_cache.py — the TiFlash-columnar-replica
    analogue): the KV scan + row decode runs once per engine state, and
    repeated analytical reads go straight from decoded columns to the
    device kernel — or, when the HBM device cache holds the block
    (store/device_cache.py), straight from device-resident columns."""

    _decode = decode_cop_batch

    def handle(region: Region, req: CopRequest) -> list[CopResponse]:
        plan: CopPlan = req.plan
        rng: KVRange = req.ranges[0]   # client sends one range per task
        s, e = clamp_range(region, rng)
        if use_cached_path(storage, plan):
            return exec_cached_cop(storage, region, plan, s, e, req)
        out = []
        cur = s
        remaining = plan.limit
        # agg subplans coalesce scan batches into ~superchunk_rows
        # superchunks before the kernel sees them: one partial-agg
        # dispatch per superchunk instead of per 64k-row scan batch.
        # Non-agg plans keep the per-batch loop — the limit
        # short-circuit below must stay chunk-at-a-time.
        sc_limit = config.superchunk_rows() if plan.is_agg else 0
        parts: list = []
        acc = 0
        staged = 0     # host bytes of the superchunk assembly buffer

        def flush_parts() -> None:
            nonlocal acc, staged
            from tidb_tpu.chunk import Chunk
            if not parts:
                return
            big = Chunk.concat_all(parts)
            n_src = len(parts)
            parts.clear()
            acc = 0
            if staged:
                memtrack.release(plan, host=staged)
                staged = 0
            if big is not None:
                out.append(exec_cop_plan(plan, big, sources=n_src))

        try:
            while True:
                batch = storage.engine.scan(cur, e, COP_SCAN_BATCH,
                                            req.start_ts,
                                            req.isolation, desc=False)
                if not batch:
                    break
                if sc_limit:
                    dec = _decode(plan, batch)
                    parts.append(dec)
                    b = memtrack.chunk_bytes(dec)
                    memtrack.consume(plan, host=b)
                    staged += b
                    acc += dec.num_rows
                    if acc >= sc_limit:
                        flush_parts()
                else:
                    resp = exec_cop_plan(plan, _decode(plan, batch))
                    out.append(resp)
                    if remaining is not None and not plan.is_agg:
                        remaining -= resp.chunk.num_rows
                        if remaining <= 0:
                            break
                if len(batch) < COP_SCAN_BATCH:
                    break
                cur = batch[-1][0] + b"\x00"
            if sc_limit:
                flush_parts()
        finally:
            # a raise mid-assembly (decode error, quota cancel from a
            # sibling worker) must not strand the staging bytes on the
            # reader's ledger until statement detach
            if staged:
                memtrack.release(plan, host=staged)
                staged = 0
        return out

    return handle


class CopClient(kv.Client):
    """Region fan-out with a worker pool (copIterator, coprocessor.go:342)."""

    def __init__(self, storage):
        self.storage = storage
        self.cache = storage.region_cache
        self.shim = storage.shim
        # remote shims execute the coprocessor in the storage process and
        # have no installable handler surface
        if getattr(self.shim, "_cop_handler", "remote") is None:
            self.shim.install_cop_handler(cop_handler(storage))
        if getattr(self.shim, "_cop_stream_handler", "remote") is None:
            from tidb_tpu.store.stream import cop_stream_handler
            self.shim.install_cop_stream_handler(cop_stream_handler(storage))

    def send(self, req: CopRequest):
        """Yields CopResponses; unordered unless req.keep_order."""
        self.storage.check_visibility(req.start_ts)
        tasks = self.cache.split_ranges_by_region(req.ranges)
        if not tasks:
            return
        from tidb_tpu import metrics
        metrics.counter(metrics.COP_TASKS, inc=len(tasks))
        coll = runtime_stats.current()
        if coll is not None:
            # send() is driven on the session thread (first next()):
            # attribute the fan-out width to the issuing reader node
            coll.note_cop_tasks(req.plan, len(tasks))
        concurrency = min(req.concurrency or config.cop_concurrency(),
                          len(tasks))
        if config.copr_stream_enabled() and \
                getattr(self.shim, "coprocessor_stream", None) is not None:
            yield from self._send_streaming(req, tasks, concurrency)
            return
        # the session's sysvar overlay is thread-local: capture it here
        # and re-install inside every pool worker so per-session knobs
        # (device on/off, cache) apply uniformly across the fan-out —
        # the runtime-stats collector, the memory tracker, the resource
        # meter AND the statement trace ride along the same way, so
        # storage-side device kernels attribute their time, bytes and
        # spans to the reader node (and tenant) that issued them
        overlay = config.current_overlay()
        mem_root = memtrack.current()
        res_meter = meter.current()
        tspan = trace.propagate()
        # consumer-gone signal, checked between tasks: teardown signals
        # it and then JOINS the pool (the copIterator.Close
        # finished-channel + wg.Wait() discipline) — a statement never
        # leaves detached workers holding scheduler slots or ledger
        # bytes past its own unwind, which is exactly what the
        # ledger_hygiene drain checks assert right after an error
        stop = threading.Event()

        def run_task(rq, rng):
            if stop.is_set():
                return []
            with config.session_overlay(overlay), \
                    runtime_stats.collecting(coll), \
                    memtrack.tracking(mem_root), \
                    meter.metering(res_meter), \
                    trace.attached(tspan):
                with trace.span("copr.task"):
                    return list(self._run_task(rq, rng))
        if concurrency <= 1 or len(tasks) == 1:
            for loc, rng in tasks:
                with trace.span("copr.task"):
                    out = self._run_task(req, rng)
                yield from out
            return
        results: "queue.Queue" = queue.Queue()
        done = object()

        def worker(task_list):
            try:
                with config.session_overlay(overlay), \
                        runtime_stats.collecting(coll), \
                        memtrack.tracking(mem_root), \
                        meter.metering(res_meter), \
                        trace.attached(tspan):
                    for _loc, rng in task_list:
                        if stop.is_set():   # consumer gone: stop at the
                            break           # next task boundary
                        with trace.span("copr.task"):
                            out = self._run_task(req, rng)
                        for resp in out:
                            results.put(resp)
                results.put(done)
            except Exception as exc:  # noqa: BLE001
                results.put(exc)

        if req.keep_order:
            # ordered at FULL concurrency: tasks run in parallel, results
            # drain strictly in task (range) order — the per-task
            # response-channel design of coprocessor.go:342-457. A
            # sliding window of `concurrency` in-flight tasks bounds both
            # memory and wasted work when the consumer stops early.
            from collections import deque
            pool = ThreadPoolExecutor(max_workers=concurrency,
                                      thread_name_prefix="cop-ord")
            try:
                it = iter(tasks)
                window: deque = deque()
                for _ in range(concurrency):
                    nxt = next(it, None)
                    if nxt is None:
                        break
                    window.append(pool.submit(run_task, req, nxt[1]))
                while window:
                    f = window.popleft()
                    nxt = next(it, None)
                    if nxt is not None:
                        window.append(pool.submit(run_task, req,
                                                  nxt[1]))
                    yield from f.result()
            finally:
                # signal, drop queued tasks, then WAIT: in-flight tasks
                # finish their current dispatch and release their slots
                # before the statement's unwind completes
                stop.set()
                pool.shutdown(wait=True, cancel_futures=True)
            return
        buckets = [tasks[i::concurrency] for i in range(concurrency)]
        pool = ThreadPoolExecutor(max_workers=concurrency,
                                  thread_name_prefix="cop")
        for b in buckets:
            pool.submit(worker, b)
        finished = 0
        try:
            while finished < concurrency:
                item = results.get()
                if item is done:
                    finished += 1
                elif isinstance(item, Exception):
                    raise item
                else:
                    yield item
        finally:
            # `results` is unbounded so no producer can block on a put;
            # the stop flag bounds the join at one in-flight task per
            # worker
            stop.set()
            pool.shutdown(wait=True)

    def _run_task(self, req: CopRequest, rng: KVRange):
        """One region task with retry (handleTask, coprocessor.go:507):
        region errors re-split the range; locks resolve."""
        bo = Backoffer(COP_MAX_BACKOFF)
        while True:
            loc = self.cache.locate(rng.start)
            sub = CopRequest(tp=req.tp, ranges=[rng], plan=req.plan,
                             start_ts=req.start_ts,
                             concurrency=1, isolation=req.isolation)
            try:
                return self.shim.coprocessor(loc.ctx, sub)
            except NotLeaderError as e:
                self.cache.on_not_leader(e)
                bo.backoff(BO_REGION_MISS, e)
            except RegionError as e:
                self.cache.invalidate(loc.region.id)
                bo.backoff(BO_REGION_MISS, e)
                # range may now span regions: re-split and recurse
                out = []
                for _l, sub_rng in self.cache.split_ranges_by_region([rng]):
                    out.extend(self._run_task(req, sub_rng))
                return out
            except ServerBusyError as e:
                bo.backoff(BO_SERVER_BUSY, e)
            except KeyLockedError as e:
                if not self.storage.resolver.resolve(bo, [e.lock]):
                    bo.backoff(BO_TXN_LOCK, e)

    # -- streaming path (tidb_tpu_copr_stream=1; ref: CmdCopStream,
    # coprocessor.go:547-555 + handleCopStreamResult resume) ---------------

    def _send_streaming(self, req: CopRequest, tasks, concurrency: int):
        """Framed partial responses, never a materialized per-region
        list. Concurrency 1 (or one task) runs tasks sequentially with
        ONE lazy in-flight stream — range order is frame order and the
        client buffers nothing. KeepOrder at full concurrency runs a
        sliding window of `concurrency` streams whose frames drain
        strictly in task (range) order from per-task credit-sized
        queues — the streaming analogue of the materialized path's
        per-task response channels (coprocessor.go:342-457), bounded by
        concurrency x credit frames instead of whole response lists.
        The unordered fan-out runs tasks in a pool draining into ONE
        BoundedFrameQueue sized to the credit window, so producers
        block (credit stall) instead of buffering when the consumer is
        slow."""
        from tidb_tpu.store.stream import BoundedFrameQueue

        credit = config.copr_stream_credit()
        # per-QUERY span tags come from client-side counters (one dict
        # per task, summed here) — the module-level stream stats are
        # process-cumulative and would cross-pollute concurrent sessions
        counters: list[dict] = []

        def new_counter() -> dict:
            c = {"frames": 0, "resumes": 0}
            counters.append(c)
            return c

        def annotate_totals() -> None:
            trace.annotate(
                cop_stream_frames=sum(c["frames"] for c in counters),
                cop_stream_resumes=sum(c["resumes"] for c in counters))

        if concurrency <= 1 or len(tasks) == 1:
            for _loc, rng in tasks:
                yield from self._run_task_stream(req, rng, new_counter())
            annotate_totals()
            return
        if req.keep_order:
            yield from self._send_streaming_ordered(
                req, tasks, concurrency, credit, new_counter)
            annotate_totals()
            return
        stop = threading.Event()
        q = BoundedFrameQueue(credit, stop)
        overlay = config.current_overlay()
        coll = runtime_stats.current()
        mem_root = memtrack.current()
        res_meter = meter.current()
        tspan = trace.propagate()
        buckets = [tasks[i::concurrency] for i in range(concurrency)]

        def worker(task_list):
            try:
                with config.session_overlay(overlay), \
                        runtime_stats.collecting(coll), \
                        memtrack.tracking(mem_root), \
                        meter.metering(res_meter), \
                        trace.attached(tspan), \
                        trace.span("copr.stream", tasks=len(task_list)):
                    for _loc, rng in task_list:
                        if stop.is_set():
                            return           # consumer gone
                        for resp in self._run_task_stream(
                                req, rng, new_counter()):
                            if not q.put(resp):
                                return       # consumer gone
                q.put_done()
            except Exception as exc:  # noqa: BLE001 — re-raised by consumer
                q.put(exc)
                q.put_done()

        pool = ThreadPoolExecutor(max_workers=concurrency,
                                  thread_name_prefix="cop-stream")
        for b in buckets:
            pool.submit(worker, b)
        try:
            yield from q.drain(len(buckets))
            annotate_totals()
        finally:
            # stop, then JOIN: q.put polls the stop event every 50ms so
            # blocked producers exit promptly, and a producer mid-frame
            # finishes its current device step and releases its slot
            # before the statement's unwind completes — no detached
            # worker outlives the statement (ledger/slot hygiene)
            stop.set()
            pool.shutdown(wait=True)

    def _send_streaming_ordered(self, req: CopRequest, tasks,
                                concurrency: int, credit: int,
                                new_counter):
        """Ordered streaming at full concurrency: up to `concurrency`
        region streams produce in parallel, each into its OWN
        credit-sized BoundedFrameQueue; the consumer drains the queues
        strictly in task order, launching the next task as each window
        slot frees. Producers past their credit window block (counted
        as credit stalls), so client buffering is bounded by
        concurrency x credit frames while storage-side scan/decode/agg
        for later ranges overlaps the consumer's drain of earlier
        ones."""
        from collections import deque
        from tidb_tpu.store.stream import BoundedFrameQueue

        stop = threading.Event()
        overlay = config.current_overlay()
        coll = runtime_stats.current()
        mem_root = memtrack.current()
        res_meter = meter.current()
        tspan = trace.propagate()
        pool = ThreadPoolExecutor(max_workers=concurrency,
                                  thread_name_prefix="cop-stream-ord")

        def launch(rng) -> BoundedFrameQueue:
            q: BoundedFrameQueue = BoundedFrameQueue(credit, stop)

            def produce():
                try:
                    with config.session_overlay(overlay), \
                            runtime_stats.collecting(coll), \
                            memtrack.tracking(mem_root), \
                            meter.metering(res_meter), \
                            trace.attached(tspan), \
                            trace.span("copr.stream"):
                        for resp in self._run_task_stream(
                                req, rng, new_counter()):
                            if not q.put(resp):
                                return       # consumer gone
                    q.put_done()
                except Exception as exc:  # noqa: BLE001 — re-raised by
                    q.put(exc)            # the consumer's drain
                    q.put_done()

            pool.submit(produce)
            return q

        try:
            it = iter(tasks)
            window: deque = deque()
            for _ in range(concurrency):
                nxt = next(it, None)
                if nxt is None:
                    break
                window.append(launch(nxt[1]))
            while window:
                q0 = window.popleft()
                nxt = next(it, None)
                if nxt is not None:
                    window.append(launch(nxt[1]))
                yield from q0.drain(1)
        finally:
            stop.set()               # producers poll it inside put()
            pool.shutdown(wait=True)

    def _run_task_stream(self, req: CopRequest, rng: KVRange,
                         counter: dict | None = None):
        """One range, streamed: frames arrive in key order; `cur` tracks
        the last ACKED range boundary. A region error, failpoint, or
        dropped connection mid-stream re-locates from `cur` and
        re-issues — frames cover contiguous, non-overlapping ranges, so
        the retry can neither duplicate nor skip rows. Crossing a region
        boundary (final frame's `range.end` before the requested end)
        continues into the next region under the same cursor.
        `counter` collects this call's frame/resume counts for per-query
        span tags."""
        from tidb_tpu import kv as _kv
        from tidb_tpu.store.stream import note_resume

        if counter is None:
            counter = {"frames": 0, "resumes": 0}

        def resumed() -> None:
            counter["resumes"] += 1
            note_resume()
        bo = Backoffer(COP_MAX_BACKOFF)
        cur = rng.start
        while True:
            loc = self.cache.locate(cur)
            sub = CopRequest(tp=req.tp, ranges=[KVRange(cur, rng.end)],
                             plan=req.plan, start_ts=req.start_ts,
                             concurrency=1, isolation=req.isolation)
            covered_to = None
            try:
                it = self.shim.coprocessor_stream(
                    loc.ctx, sub, credit=config.copr_stream_credit(),
                    frame_bytes=config.copr_stream_frame_bytes())
                for frame in it:
                    counter["frames"] += 1
                    # chunk is a Chunk (scan/filter), a GroupResult
                    # (device partial agg — no num_rows), or None
                    if frame.chunk is not None and \
                            getattr(frame.chunk, "num_rows", 1):
                        yield CopResponse(chunk=frame.chunk,
                                          range=frame.range)
                    cur = frame.range.end        # acked through here
                    if frame.last:
                        covered_to = frame.range.end
            except (NotLeaderError, RegionError, ServerBusyError,
                    KeyLockedError, _kv.StreamInterruptedError) as e:
                if covered_to is not None:
                    # the final frame was already acked — the stream's
                    # work is DONE and only protocol closure failed.
                    # Resuming would re-scan from `cur`, which for an
                    # open-ended final frame is b"" (= the very start):
                    # the one way this loop could duplicate rows.
                    pass
                elif isinstance(e, NotLeaderError):
                    self.cache.on_not_leader(e)
                    bo.backoff(BO_REGION_MISS, e)
                    resumed()
                    continue
                elif isinstance(e, RegionError):
                    self.cache.invalidate(loc.region.id)
                    bo.backoff(BO_REGION_MISS, e)
                    resumed()
                    continue
                elif isinstance(e, _kv.StreamInterruptedError):
                    # the stream died with the connection: the region
                    # epoch we hold may be from before the store plane
                    # restarted — re-resolve instead of re-issuing the
                    # same stale ctx forever
                    self.cache.invalidate(loc.region.id)
                    bo.backoff(BO_REGION_MISS, e)
                    resumed()
                    continue
                elif isinstance(e, ServerBusyError):
                    bo.backoff(BO_SERVER_BUSY, e)
                    resumed()
                    continue
                else:   # KeyLockedError
                    if not self.storage.resolver.resolve(bo, [e.lock]):
                        bo.backoff(BO_TXN_LOCK, e)
                    resumed()
                    continue
            if covered_to is None:
                covered_to = cur
            if not covered_to:
                return          # open-ended coverage: nothing beyond
            if rng.end and covered_to >= rng.end:
                return          # requested range fully covered
            cur = covered_to    # region ended early: continue next region
