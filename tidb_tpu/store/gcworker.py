"""MVCC garbage collection worker.

Reference: /root/reference/store/tikv/gcworker/gc_worker.go — a single
elected leader ticks (gc_worker.go:117-214), computes the safepoint
(now - gc_life_time), resolves all locks below it (:325), drains the
delete-range queue left by DDL (ddl/delete_range.go), then runs
region-parallel GC RPCs (doGC :482). safepoint.go: stores reject reads
below the safepoint.

Here the leader lease lives in a plain KV key (the reference uses rows in
mysql.tidb, gc_worker.go:550) so multiple in-process "servers" sharing a
store elect exactly one worker; the tick is driven explicitly by
run_once() rather than a background goroutine — callers (tests, the
session's housekeeping, a real server's timer thread) own the cadence.
"""

from __future__ import annotations

import threading
import time
import uuid
from concurrent.futures import ThreadPoolExecutor

from tidb_tpu import kv
from tidb_tpu.kv import GCTooEarlyError
from tidb_tpu.meta import Meta
from tidb_tpu.store.backoff import BO_REGION_MISS, Backoffer
from tidb_tpu.store.oracle import compose_ts, physical_ms

__all__ = ["GCWorker", "GCTooEarlyError", "DEFAULT_GC_LIFE_TIME_MS"]

DEFAULT_GC_LIFE_TIME_MS = 10 * 60 * 1000    # ref: gcDefaultLifeTime 10m
GC_SAFEPOINT_KEY = b"m_gcSafePoint"
GC_LEADER_KEY = b"m_gcLeader"
GC_LEADER_LEASE_MS = 2 * 60 * 1000          # ref: gcWorkerLease 2m
GC_CONCURRENCY = 4
RESOLVE_MAX_BACKOFF = 20000


class GCWorker:
    def __init__(self, storage, gc_life_time_ms: int =
                 DEFAULT_GC_LIFE_TIME_MS):
        self.storage = storage
        self.gc_life_time_ms = gc_life_time_ms
        self.uuid = uuid.uuid4().hex[:12]
        self._mu = threading.Lock()

    # -- leader lease --------------------------------------------------------

    def _try_lead(self, now_ms: int) -> bool:
        """Acquire/renew the leader lease (ref: gc_worker.go:550
        checkLeader over mysql.tidb lease rows)."""
        txn = self.storage.begin()
        try:
            raw = txn.get(GC_LEADER_KEY)
            if raw is not None:
                try:
                    owner, expiry = raw.decode().split(":")
                    expiry = int(expiry)
                except ValueError:
                    owner, expiry = "", 0   # corrupt lease: take over
                if owner != self.uuid and expiry > now_ms:
                    return False
            txn.set(GC_LEADER_KEY,
                    f"{self.uuid}:{now_ms + GC_LEADER_LEASE_MS}".encode())
            txn.commit()
            return True
        except kv.RetryableError:
            return False
        finally:
            if txn.valid:
                txn.rollback()

    # -- safepoint -----------------------------------------------------------

    def saved_safepoint(self) -> int:
        txn = self.storage.begin()
        try:
            raw = txn.get(GC_SAFEPOINT_KEY)
            return int(raw) if raw else 0
        finally:
            txn.rollback()

    def _save_safepoint(self, sp: int) -> None:
        txn = self.storage.begin()
        try:
            txn.set(GC_SAFEPOINT_KEY, b"%d" % sp)
            txn.commit()
        except Exception:
            txn.rollback()
            raise
        # push to the store for read-visibility checks (safepoint.go watch)
        self.storage.update_safepoint(sp)

    # -- the tick ------------------------------------------------------------

    def run_once(self, now_ts: int | None = None) -> dict:
        """One GC cycle; returns stats. No-op unless leader and the new
        safepoint advances past the saved one."""
        if now_ts is None:
            now_ts = self.storage.current_ts()
        now_ms = physical_ms(now_ts)
        if not self._try_lead(now_ms):
            return {"leader": False}
        safepoint = compose_ts(max(0, now_ms - self.gc_life_time_ms), 0)
        # never advance past an in-flight reorg's read snapshot (the
        # reference keeps the safepoint below active DDL reorg snapshots)
        reorg = self._min_reorg_snapshot()
        if reorg is not None:
            safepoint = min(safepoint, reorg)
        prev = self.saved_safepoint()
        if safepoint <= prev:
            return {"leader": True, "safepoint": prev, "advanced": False}

        locks = self._resolve_locks(safepoint)
        # publish BEFORE destroying anything: readers in
        # (prev, safepoint) must start failing check_visibility before
        # their versions can disappear
        self._save_safepoint(safepoint)
        ranges = self._drain_delete_ranges(safepoint)
        pruned = self._gc_regions(safepoint)
        return {"leader": True, "safepoint": safepoint, "advanced": True,
                "resolved_locks": locks, "delete_ranges": ranges,
                "pruned": pruned}

    def _min_reorg_snapshot(self) -> int | None:
        txn = self.storage.begin()
        try:
            job = Meta(txn).first_job()
        finally:
            txn.rollback()
        if job is not None and job.snapshot_ver:
            return job.snapshot_ver
        return None

    # -- phases --------------------------------------------------------------

    def _region_rpc(self, key: bytes, fn):
        """fn(loc) with the standard region-error retry discipline
        (ref: region_request.go): invalidate + re-locate on stale epoch."""
        bo = Backoffer(RESOLVE_MAX_BACKOFF)
        while True:
            loc = self.storage.region_cache.locate(key)
            try:
                return loc, fn(loc)
            except kv.NotLeaderError as e:
                self.storage.region_cache.on_not_leader(e)
                bo.backoff(BO_REGION_MISS, e)
            except kv.RegionError as e:
                self.storage.region_cache.invalidate(loc.region.id)
                bo.backoff(BO_REGION_MISS, e)

    def _each_region_rpc(self, fn):
        """Run fn over every region left to right; yields results."""
        key = b""
        while True:
            loc, out = self._region_rpc(key, fn)
            yield loc, out
            if not loc.region.end:
                return
            key = loc.region.end

    def _resolve_locks(self, safepoint: int) -> int:
        """Any lock below the safepoint belongs to a dead or paused txn:
        roll it forward/back before its intent becomes unreachable
        (ref: gc_worker.go:325 resolveLocks)."""
        n = 0
        for _loc, locks in self._each_region_rpc(
                lambda loc: self.storage.shim.kv_scan_lock(loc.ctx,
                                                           safepoint)):
            if locks:
                # every lock below the safepoint is gc_life_time old: its
                # TTL has long expired, so resolve rolls it forward/back
                bo = Backoffer(RESOLVE_MAX_BACKOFF)
                self.storage.resolver.resolve(bo, locks)
                n += len(locks)
        return n

    def _drain_delete_ranges(self, safepoint: int) -> int:
        """Physically delete ranges queued by DDL drops, but only once the
        safepoint has passed the drop itself — older snapshots may still
        legitimately read the data (ref: gc_worker.go:325 deleteRanges
        over mysql.gc_delete_range, filtered by its ts column)."""
        self._reseal_orphans()
        txn = self.storage.begin()
        try:
            pending = [r for r in Meta(txn).pending_delete_ranges()
                       if 0 < r[4] <= safepoint]   # sealed + safepoint past
        finally:
            txn.rollback()
        for qkey, _job, start, end, _ts in pending:
            cur = start
            while True:
                loc, _ = self._region_rpc(
                    cur, lambda loc, cur=cur: self.storage.shim.
                    kv_delete_range(
                        loc.ctx, max(cur, loc.region.start or cur),
                        min(end, loc.region.end) if loc.region.end
                        else end))
                if not loc.region.end or loc.region.end >= end:
                    break
                cur = loc.region.end
            txn = self.storage.begin()
            try:
                Meta(txn).remove_delete_range(qkey)
                txn.commit()
            except Exception:
                txn.rollback()
                raise
        return len(pending)

    def _reseal_orphans(self) -> None:
        """Seal unsealed ranges whose DDL job already finished — covers a
        worker that crashed between its final job txn and the seal, so no
        dropped data leaks forever."""
        txn = self.storage.begin()
        try:
            m = Meta(txn)
            orphan_jobs = {job_id for _k, job_id, _s, _e, ts
                           in m.pending_delete_ranges()
                           if ts == 0 and m.history_job(job_id) is not None}
            for job_id in orphan_jobs:
                m.seal_delete_ranges(job_id, txn.start_ts)
            if orphan_jobs:
                txn.commit()
            else:
                txn.rollback()
        except Exception:
            if txn.valid:
                txn.rollback()

    def _gc_regions(self, safepoint: int) -> int:
        """Region-parallel GC RPCs (ref: doGC gc_worker.go:482)."""
        starts = [loc.region.start
                  for loc, _ in self._each_region_rpc(lambda loc: None)]
        total = 0
        with ThreadPoolExecutor(max_workers=GC_CONCURRENCY,
                                thread_name_prefix="gc") as pool:
            for _loc, pruned in pool.map(
                    lambda k: self._region_rpc(
                        k, lambda loc: self.storage.shim.kv_gc(loc.ctx,
                                                               safepoint)),
                    starts):
                total += int(pruned or 0)
        return total
