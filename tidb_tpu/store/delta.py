"""MVCC delta store: the columnar/HBM cache planes stay hot under
concurrent OLTP writes.

Before this module, HTAP was read-only in practice: ANY committed write
bumped the engine's data_version and wholesale-invalidated both the
columnar chunk cache (store/chunk_cache.py) and the HBM device cache
(store/device_cache.py) — a trickle of new-order/payment updates
re-colded gigabytes of device-resident columns. PR 9's heartbeat fix
removed *false* invalidation; this removes the true-write cliff:

* **Capture.** The MVCC engine journals committed ROW mutations here
  per table — (handle, key, value|None, commit_ts), sorted by commit
  ts — under the engine lock, atomically with the commit becoming
  readable (mockstore/mvcc.py commit/resolve_lock). Index-key commits
  advance a per-table index watermark instead (index layouts cannot be
  patched by row values). data_version now bumps only for structural
  changes (meta/DDL, GC, delete-range, bulk import).

* **Serve.** A cached block filled at fill_ts serves a reader at
  read_ts as `base ⋈ delta`: the journal window (fill_ts, read_ts] is
  folded over the base — upserts/deletes merged on row handles, the
  result memoized on the base chunk per watermark — instead of
  discarding the block (store/copr.py `_cached_range_chunk`). The HBM
  cache patches its resident device arrays in place the same way
  (store/device_cache.py `apply_pending`: validity/value scatters plus
  tail appends into the padding, dict columns extended incrementally).

* **Merge.** Accumulated deltas fold into new base blocks at snapshot
  boundaries: the background merge promotes the read path's memoized
  base⋈delta results to cache entries, re-fills lagging HBM blocks
  under the device scheduler's dispatch slots (merges never starve
  serving), then truncates the journal below the new floor. Triggers:
  staged rows (`tidb_tpu_delta_merge_rows`), delta/base row ratio
  (`tidb_tpu_delta_merge_ratio_pct`), and the SERVER shed chain —
  staged bytes are billed to a server-scope `delta-store` memtrack
  node, and the registered spill action forces an early merge so
  `GET /shed` and admission-driven shedding reclaim them.

MVCC correctness: the journal is an ACCELERATOR — the engine remains
the source of truth. A reader at ts T applies only deltas with
commit_ts <= T, so it can never see a later commit; truncation below a
live entry's fill_ts is answered with STALE, which drops the entry back
to a real scan. Pending Percolator locks are handled by the engine's
serve-time `locked_in_range` veto, not by this module.
"""

from __future__ import annotations

import bisect
import threading
import weakref
from collections import OrderedDict

import numpy as np

from tidb_tpu import config, memtrack, metrics
from tidb_tpu.store import oracle
from tidb_tpu.util import failpoint

__all__ = ["DeltaStore", "PendingDelta", "STALE", "tracker",
           "record_handles"]

# pending() answer when the journal was truncated below the asked
# window: the entry can no longer be patched forward — drop it and
# re-scan (the engine still has every version)
STALE = object()

# ~fixed per-record journal overhead (tuple + list slot + ts entry)
_REC_OVERHEAD = 96

_tracker_lock = threading.Lock()
_tracker: memtrack.MemTracker | None = None   # guarded-by: _tracker_lock

# every live store, for the single server-wide shed action; weak so
# short-lived test storages don't accumulate forever
_stores: "weakref.WeakSet[DeltaStore]" = \
    weakref.WeakSet()               # guarded-by: _tracker_lock
_shed_registered = False            # guarded-by: _tracker_lock
# staged rows across every live store (the DELTA_ROWS gauge is
# process-wide, stores are per-storage)
_rows_total = [0]                   # guarded-by: _tracker_lock

# serializes base⋈delta memo access on cached base chunks (patch_chunk
# and the merge's promotion walk share it)
_patch_mu = threading.Lock()


def tracker() -> memtrack.MemTracker:
    """The shared server-scope tracker node delta staging bills
    (label `delta-store`, host ledger)."""
    global _tracker
    with _tracker_lock:
        if _tracker is None:
            _tracker = memtrack.server_node("delta-store")
        return _tracker


def _shed_all() -> None:
    """The registered memtrack spill action: force an early merge in
    every live store, folding + truncating staged deltas (frees the
    staged journal bytes on the delta-store ledger). Snapshot under the
    lock — iterating the WeakSet bare races a concurrent store
    construction's add() (same discipline as device_cache._shed_all)."""
    with _tracker_lock:
        stores = list(_stores)
    for store in stores:
        store.merge(trigger="shed")


def _note_rows(delta: int) -> int:
    with _tracker_lock:
        _rows_total[0] += delta
        return _rows_total[0]


def _release_staged(staged: list) -> None:
    """GC finalizer: credit back whatever a dead store still held."""
    freed, staged[0] = staged[0], 0
    rows, staged[1] = staged[1], 0
    if freed:
        tracker().release(host=freed)
    if rows:
        metrics.gauge(metrics.DELTA_ROWS, _note_rows(-rows))


def _register(store: "DeltaStore") -> None:
    global _shed_registered
    with _tracker_lock:
        _stores.add(store)
        if not _shed_registered:
            memtrack.SERVER.add_spill_action(_shed_all)
            _shed_registered = True


def record_handles(keys) -> np.ndarray:
    """Row handles of raw record keys, vectorized: a record key is the
    fixed 19-byte t{tid:8}_r{handle:8} layout (tablecodec), so the
    handle is the sign-flipped big-endian tail. Falls back to the codec
    on anything unexpected."""
    n = len(keys)
    buf = b"".join(keys)
    if len(buf) == 19 * n:
        tail = np.frombuffer(buf, dtype=np.uint8).reshape(n, 19)[:, 11:]
        u = np.ascontiguousarray(tail).view(">u8").reshape(n)
        return (u.astype(np.uint64) ^ np.uint64(1 << 63)).view(np.int64)
    from tidb_tpu import tablecodec
    return np.fromiter(
        (tablecodec.decode_record_key(k)[1] for k in keys),
        dtype=np.int64, count=n)


class PendingDelta:
    """The net effect of one journal window over one key range:
    last-wins upserts (raw rows for plan-layout decode, handles
    aligned) and deletes, plus the watermark the consumer advances its
    fill_ts to after applying."""

    __slots__ = ("watermark", "upsert_rows", "upsert_handles",
                 "delete_handles", "decoded")

    def __init__(self, watermark: int, upsert_rows: list,
                 upsert_handles: np.ndarray,
                 delete_handles: np.ndarray):
        self.watermark = watermark
        self.upsert_rows = upsert_rows          # [(key, value)] order-
        self.upsert_handles = upsert_handles    # aligned with handles
        self.delete_handles = delete_handles
        self.decoded = None     # plan-layout chunk, set by the caller


class _TableDeltas:
    __slots__ = ("records", "ts", "index_commits", "floor", "rows",
                 "bytes", "base_rows")

    def __init__(self):
        self.records: list = []        # (cts, handle, key, value|None)
        self.ts: list = []             # commit_ts of records, sorted
        self.index_commits: list = []  # sorted commit_ts of index keys
        self.floor = 0                 # journal truncated at/below this
        self.rows = 0
        self.bytes = 0
        self.base_rows = 0             # largest cached base seen


class DeltaStore:
    """Per-storage staged delta journal + fold/merge driver. Thread
    safety: `_mu` guards the table map and counters; every cache /
    memtrack / metrics call happens with it dropped (ingest runs under
    the ENGINE lock — see mockstore/mvcc.py — so this lock must stay a
    near-leaf)."""

    def __init__(self, storage):
        self._storage = storage
        self._mu = threading.Lock()
        self._tables: dict[int, _TableDeltas] = {}   # guarded-by: _mu
        # [bytes, rows] shared with a GC finalizer: a store dropped
        # without close() still returns its ledger share
        self._staged = [0, 0]                        # guarded-by: _mu
        self._merging = False                        # guarded-by: _mu
        weakref.finalize(self, _release_staged, self._staged)
        _register(self)

    def enabled(self) -> bool:
        """Capture on? Flipping `tidb_tpu_delta_store` OFF while the
        journal holds staged rows must not strand them: those commits
        never bumped data_version, and with the store disabled nothing
        would fold them in — cached entries would serve PRE-update data
        indefinitely. The first consult after the flip flushes: drop
        the journal and bump the engine's structural version once, so
        every cached entry re-fills from the legacy contract."""
        if config.delta_store_enabled():
            return True
        if self._staged[1]:
            self._flush_on_disable()
        return False

    def _flush_on_disable(self) -> None:
        with self._mu:
            freed, self._staged[0] = self._staged[0], 0
            rows, self._staged[1] = self._staged[1], 0
            self._tables.clear()
        if not rows:
            return      # another thread flushed first
        # bump AFTER the journal is gone, with _mu dropped (the engine
        # lock is re-entrant here when the consult came from the
        # engine's own capture check)
        engine = self._storage.engine
        with engine._mu:
            engine.data_version += 1
        if freed:
            tracker().release(host=freed)
        metrics.gauge(metrics.DELTA_ROWS, _note_rows(-rows))

    # -- capture (called by the MVCC engine, under the engine lock) ---------

    def ingest(self, records: list, idx_notes: list) -> bool:
        """Journal one commit's record mutations + index notes.
        records: [(table_id, handle, key, value|None, commit_ts)].
        -> False when capture is off (the engine then falls back to the
        legacy data_version bump)."""
        if not self.enabled():
            return False
        add_bytes = 0
        add_rows = 0
        with self._mu:
            for tid, handle, key, value, cts in records:
                td = self._tables.get(tid)
                if td is None:
                    td = self._tables[tid] = _TableDeltas()
                rec = (cts, handle, key, value)
                if not td.ts or cts >= td.ts[-1]:
                    td.records.append(rec)
                    td.ts.append(cts)
                else:   # out-of-order commit: keep the journal sorted
                    i = bisect.bisect_right(td.ts, cts)
                    td.records.insert(i, rec)
                    td.ts.insert(i, cts)
                nb = len(key) + (len(value) if value else 0) + \
                    _REC_OVERHEAD
                td.rows += 1
                td.bytes += nb
                add_bytes += nb
                add_rows += 1
            for tid, cts in idx_notes:
                td = self._tables.get(tid)
                if td is None:
                    td = self._tables[tid] = _TableDeltas()
                ic = td.index_commits
                if not ic or cts >= ic[-1]:
                    ic.append(cts)
                else:
                    bisect.insort(ic, cts)
            self._staged[0] += add_bytes
            self._staged[1] += add_rows
        if add_bytes:
            # lint: exempt[paired-resource] staged journal bytes: released when the merge truncates (or close/shed); a GC finalizer backstops dead stores
            tracker().consume(host=add_bytes)
        if add_rows:
            metrics.gauge(metrics.DELTA_ROWS, _note_rows(add_rows))
        self._maybe_trigger()
        return True

    # -- read-side queries ---------------------------------------------------

    def pending(self, table_id: int, s: bytes, e: bytes, lo_ts: int,
                hi_ts: int):
        """Net delta for record keys in [s, e) committed in
        (lo_ts, hi_ts]: a PendingDelta, None when the window holds
        nothing for the range, or STALE when the journal was truncated
        above lo_ts (the entry can't be patched — drop and re-scan)."""
        with self._mu:
            td = self._tables.get(table_id)
            if td is None:
                return None
            if td.floor > lo_ts:
                return STALE
            if not td.ts or td.ts[-1] <= lo_ts:
                return None
            lo_i = bisect.bisect_right(td.ts, lo_ts)
            hi_i = bisect.bisect_right(td.ts, hi_ts)
            if hi_i <= lo_i:
                return None
            window = td.records[lo_i:hi_i]
            watermark = td.ts[hi_i - 1]
        net: "OrderedDict[int, tuple]" = OrderedDict()
        for _cts, handle, key, value in window:
            if key < s or (e and key >= e):
                continue
            net.pop(handle, None)       # last-wins, append order kept
            net[handle] = (key, value)
        if not net:
            return None
        upsert_rows = []
        upsert_handles = []
        deletes = []
        for handle, (key, value) in net.items():
            if value is None:
                deletes.append(handle)
            else:
                upsert_rows.append((key, value))
                upsert_handles.append(handle)
        return PendingDelta(
            watermark, upsert_rows,
            np.asarray(upsert_handles, dtype=np.int64),
            np.asarray(deletes, dtype=np.int64))

    def index_stale(self, table_id: int, fill_ts: int,
                    read_ts: int) -> bool:
        """Did any index-key commit land in (fill_ts, read_ts]? Index
        layouts can't be patched from row values, so a stale index
        entry is dropped and re-filled at a newer snapshot."""
        with self._mu:
            td = self._tables.get(table_id)
            if td is None:
                return False
            if td.floor > fill_ts:
                return True
            ic = td.index_commits
            i = bisect.bisect_right(ic, fill_ts)
            return i < len(ic) and ic[i] <= read_ts

    def note_base_rows(self, table_id: int, nrows: int) -> None:
        """Feed the delta/base ratio trigger the size of a base block
        the read path just served."""
        with self._mu:
            td = self._tables.get(table_id)
            if td is not None and nrows > td.base_rows:
                td.base_rows = nrows

    # -- host-side base ⋈ delta ---------------------------------------------

    def patch_chunk(self, cache, key, plan, chunk, pend: PendingDelta):
        """The cached base chunk with `pend` folded in — upserts/deletes
        merged on row handles, result sorted by handle (scan order) and
        memoized on the base per watermark so repeated hot reads at one
        delta state pay the merge once. -> merged chunk (its
        _scan_handles attached, its decoded upserts left on
        pend.decoded for the device layer), or None when the base
        carries no handles (unpatchable: caller drops the entry)."""
        base_handles = getattr(chunk, "_scan_handles", None)
        if base_handles is None:
            return None
        with _patch_mu:
            memo = getattr(chunk, "_delta_memo", None)
            hit = memo.get(pend.watermark) if memo else None
            if hit is not None:
                return hit
        from tidb_tpu.store.copr import decode_cop_batch
        dchunk = decode_cop_batch(plan, pend.upsert_rows)
        pend.decoded = dchunk
        affected = np.concatenate([pend.upsert_handles,
                                   pend.delete_handles])
        keep = ~np.isin(base_handles, affected)
        kept_idx = np.flatnonzero(keep)
        kept = chunk.take(kept_idx)
        if dchunk.num_rows:
            merged = kept.concat(dchunk)
            mh = np.concatenate([base_handles[kept_idx],
                                 pend.upsert_handles])
            order = np.argsort(mh, kind="stable")
            merged = merged.take(order)
            mh = mh[order]
        else:
            merged, mh = kept, base_handles[kept_idx]
        merged._scan_handles = mh
        self.note_base_rows(plan.table.id, len(base_handles))
        from tidb_tpu.store.chunk_cache import _chunk_bytes
        cost = _chunk_bytes(merged)
        evicted = 0
        with _patch_mu:
            memo = getattr(chunk, "_delta_memo", None)
            if memo is None:
                memo = chunk._delta_memo = OrderedDict()
            if pend.watermark not in memo:
                memo[pend.watermark] = merged
                while len(memo) > 2:
                    _w, old = memo.popitem(last=False)
                    evicted += _chunk_bytes(old)
            else:
                merged = memo[pend.watermark]
                cost = 0
        # memoized merges ride the base entry's budget share, exactly
        # like the filter memos (evicting the base drops them all)
        if cost or evicted:
            cache.add_cost(key, cost - evicted)
        return merged

    def best_memo(self, chunk):
        """Newest memoized base⋈delta of a cached base, as
        (watermark, merged_chunk) — the merge's promotion source."""
        with _patch_mu:
            memo = getattr(chunk, "_delta_memo", None)
            if not memo:
                return None
            w = max(memo)
            return w, memo[w]

    # -- merge ---------------------------------------------------------------

    def _maybe_trigger(self) -> None:
        """Spawn a background merge when a table's staged rows cross
        the row threshold or the delta/base ratio. Cheap enough for the
        ingest path: two int compares per table touched."""
        rows_cap = config.delta_merge_rows()
        ratio = config.delta_merge_ratio_pct()
        trigger = None
        with self._mu:
            if self._merging:
                return
            for td in self._tables.values():
                if td.rows >= rows_cap:
                    trigger = "rows"
                    break
                if ratio and td.base_rows and \
                        td.rows * 100 >= td.base_rows * ratio:
                    trigger = "ratio"
                    break
        if trigger is not None:
            # supervised one-shot (util/supervisor.py): a merge that
            # crashes (device fault mid-refill, injected delta/merge
            # failpoint) retries with counted backoff instead of
            # leaving the journal to grow unmerged forever
            from tidb_tpu.util import supervisor
            threading.Thread(
                target=supervisor.run_once, name="delta-merge",
                args=("delta-merge", lambda: self.merge(trigger)),
                daemon=True).start()

    def merge(self, trigger: str = "rows") -> int:
        """Fold staged deltas into new base blocks and truncate the
        journal. -> journal rows released. Serving stays correct (and
        mostly warm) throughout: promotion reuses the read path's
        memoized base⋈delta results, HBM refills take a scheduler
        dispatch slot each, and readers racing the truncation get
        STALE -> re-scan."""
        from tidb_tpu import trace
        with self._mu:
            if self._merging:
                return 0
            self._merging = True
            tids = list(self._tables)
        freed_rows = 0
        try:
            # background merges run untraced; a SHED-forced merge fires
            # on the admitting statement's thread, where this span puts
            # the fold cost on that statement's timeline
            with trace.span("delta.merge", trigger=trigger):
                for tid in tids:
                    freed_rows += self._merge_table(tid)
        finally:
            with self._mu:
                self._merging = False
        if freed_rows:
            metrics.counter(metrics.DELTA_MERGES, {"trigger": trigger})
            metrics.gauge(metrics.DELTA_ROWS, _note_rows(-freed_rows))
        return freed_rows

    def _merge_table(self, tid: int) -> int:
        # injectable merge-worker crash: fires before any cache is
        # touched, so a raise leaves serving state intact and the
        # supervisor's retry starts from scratch
        failpoint.eval("delta/merge", tid)
        storage = self._storage
        with self._mu:
            td = self._tables.get(tid)
            if td is None or (not td.ts and not td.index_commits):
                return 0
            target = max(td.ts[-1] if td.ts else 0,
                         td.index_commits[-1] if td.index_commits else 0)
        engine = storage.engine
        cc = storage.chunk_cache
        dc = getattr(storage, "device_cache", None)
        dv_now = engine.data_version
        promoted: dict = {}     # chunk key -> (watermark, merged chunk)
        floors = []
        for key, dv, fill_ts, chunk in cc.snapshot_table(tid):
            if dv != dv_now:
                cc.drop(key)            # structurally dead anyway
                continue
            if fill_ts >= target:
                floors.append(fill_ts)
                continue
            if key[3] is not None:      # index entry: unpatchable
                if self.index_stale(tid, fill_ts, target):
                    cc.drop(key)
                else:
                    floors.append(fill_ts)
                continue
            memo = self.best_memo(chunk)
            if memo is None or memo[0] <= fill_ts:
                # cold since the writes landed: re-colding it is honest
                cc.drop(key)
                continue
            w, merged = memo
            cc.put(key, dv, w, merged)
            promoted[key] = (w, merged)
            floors.append(w)
        if dc is not None:
            from tidb_tpu import sched
            for dkey, dv, fill_ts in dc.snapshot_table(tid):
                if dv != dv_now:
                    dc.drop(dkey)
                    continue
                if fill_ts >= target:
                    floors.append(fill_ts)
                    continue
                pro = promoted.get(dkey[0])
                if pro is None:
                    dc.drop(dkey)
                    continue
                w, merged = pro
                # re-fill under a dispatch slot: merge uploads compete
                # with serving through the same global window instead
                # of starving it
                dc.drop(dkey)
                with sched.device_slot():
                    dc.fill(dkey, dv, w, merged)
                floors.append(w)
        floor = min(floors, default=target)
        retain = config.delta_retain_ms()
        if retain > 0:
            # store-plane mode: this node's own caches say nothing about
            # remote fleet caches, whose fill snapshots only reach us as
            # journal-window pulls. Keep a wall-clock window of journal
            # so a remote fill at most `retain` ms old still patches
            # instead of going STALE -> full re-fill
            floor = min(floor, oracle.retention_ts(retain))
        freed_bytes = 0
        freed_rows = 0
        with self._mu:
            td = self._tables.get(tid)
            if td is None:
                return 0
            cut = bisect.bisect_right(td.ts, floor)
            for _cts, _h, key, value in td.records[:cut]:
                freed_bytes += len(key) + \
                    (len(value) if value else 0) + _REC_OVERHEAD
            del td.records[:cut], td.ts[:cut]
            freed_rows = cut
            td.rows -= cut
            td.bytes -= freed_bytes
            icut = bisect.bisect_right(td.index_commits, floor)
            del td.index_commits[:icut]
            td.floor = max(td.floor, floor)
            self._staged[0] -= freed_bytes
            self._staged[1] -= freed_rows
        if freed_bytes:
            tracker().release(host=freed_bytes)
        return freed_rows

    # -- introspection / lifecycle ------------------------------------------

    def rows_current(self) -> int:
        with self._mu:
            return self._staged[1]

    def staged_bytes(self) -> int:
        with self._mu:
            return self._staged[0]

    def snapshot(self) -> dict:
        with self._mu:
            return {"tables": len(self._tables),
                    "rows": self._staged[1],
                    "bytes": self._staged[0]}

    def close(self) -> None:
        """Drop the journal, credit the ledger back (the caches are
        going away with the storage; nothing left to fold into)."""
        with self._mu:
            freed, self._staged[0] = self._staged[0], 0
            rows, self._staged[1] = self._staged[1], 0
            self._tables.clear()
        if freed:
            tracker().release(host=freed)
        if rows:
            metrics.counter(metrics.DELTA_MERGES, {"trigger": "close"})
            metrics.gauge(metrics.DELTA_ROWS, _note_rows(-rows))
