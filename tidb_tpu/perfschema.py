"""PERFORMANCE_SCHEMA statement events + the statement digest summary.

Reference: /root/reference/perfschema/const.go:120-298 — the
events_statements_current / events_statements_history virtual tables.
Process-wide: a per-session current-event slot plus a bounded history
ring; every non-internal statement records its SQL, wall time, phase
breakdown (parse/plan/execute/commit, from the trace span tree), row
count and error state. Served as memtables by the planner, exactly like
INFORMATION_SCHEMA.

The digest summary (`events_statements_summary_by_digest`) aggregates
repeated statements under one normalized-SQL digest — literals stripped
via the real lexer, so `SELECT * FROM t WHERE id = 7` and `... = 8`
share a row — with exec counts, sum/max latency, the phase breakdown,
and per-digest operator hot spots from the runtime-stats collector
(ref: the reference's statement summary tables,
util/stmtsummary/statement_summary.go)."""

from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict, deque

__all__ = ["stmt_begin", "stmt_end", "current_events", "history_events",
           "normalize_sql", "sql_digest", "digest_record",
           "digest_summary", "memo_record", "memo_snapshot", "memo_reset",
           "HISTORY_CAP", "SUMMARY_CAP"]

HISTORY_CAP = 1024
SUMMARY_CAP = 512          # distinct digests kept (LRU beyond)

_lock = threading.Lock()
_history: deque = deque(maxlen=HISTORY_CAP)          # guarded-by: _lock
_current: dict[int, dict] = {}       # guarded-by: _lock  (sid -> event)
_event_seq = 0                       # guarded-by: _lock
# digest -> record
_summary: "OrderedDict[str, dict]" = OrderedDict()   # guarded-by: _lock


def stmt_begin(session_id: int, sql: str) -> dict:
    global _event_seq
    with _lock:
        _event_seq += 1
        ev = {
            "thread_id": session_id,
            "event_id": _event_seq,
            "sql_text": sql[:1024],
            "state": "running",
            "timer_start_us": int(time.time() * 1e6),
            "timer_wait_ns": 0,
            "parse_ns": 0, "plan_ns": 0, "exec_ns": 0, "commit_ns": 0,
            "rows": 0,
            "error": None,
        }
        _current[session_id] = ev
        return ev


def stmt_end(ev: dict, root=None, rows: int = 0,
             error: str | None = None) -> None:
    from tidb_tpu import trace
    with _lock:
        ev["state"] = "error" if error else "completed"
        ev["error"] = error and error[:256]
        ev["rows"] = rows
        if root is not None:
            ev["timer_wait_ns"] = root.duration_ns
            for phase in ("parse", "plan", "execute", "commit"):
                key = ("exec" if phase == "execute" else phase) + "_ns"
                ev[key] = trace.phase_ns(root, phase)
        _history.append(dict(ev))


def session_closed(session_id: int) -> None:
    with _lock:
        _current.pop(session_id, None)


def current_events() -> list[dict]:
    with _lock:
        return [dict(ev) for _sid, ev in sorted(_current.items())]


def history_events() -> list[dict]:
    with _lock:
        return [dict(ev) for ev in _history]


# -- statement digest summary ----------------------------------------------


def normalize_sql(sql: str) -> str:
    """Literal-stripped canonical form: numeric/string literals become
    `?`, keywords uppercase, identifiers lowercase, one space between
    tokens. Tokenized by the real lexer so quoting/comments can't fool
    it; unlexable text falls back to whitespace collapse."""
    from tidb_tpu.parser.lexer import Lexer, TokenType
    try:
        toks = Lexer(sql).tokens()
    except Exception:  # noqa: BLE001 - redacted/garbled text must still
        return " ".join(sql.split())   # produce a stable digest
    out = []
    for t in toks:
        if t.tp == TokenType.EOF:
            break
        if t.tp in (TokenType.INT, TokenType.DECIMAL, TokenType.FLOAT,
                    TokenType.STRING):
            out.append("?")
        elif t.tp == TokenType.KEYWORD:
            out.append(str(t.val).upper())
        elif t.tp == TokenType.IDENT:
            out.append(str(t.val).lower())
        else:
            out.append(str(t.val))
    return " ".join(out)


# repeated identical SQL is the digest table's whole point: memoize the
# (re-)lex. Only short statements are cached — a multi-MB bulk INSERT
# would pin its whole text as a cache key.
_digest_lock = threading.Lock()
# guarded-by: _digest_lock
_digest_cache: "OrderedDict[str, tuple[str, str]]" = OrderedDict()
_DIGEST_CACHE_CAP = 256
_DIGEST_CACHE_MAX_SQL = 8192


def sql_digest(sql: str) -> tuple[str, str]:
    """-> (digest hex, normalized text). LRU-memoized for short SQL."""
    cacheable = len(sql) <= _DIGEST_CACHE_MAX_SQL
    if cacheable:
        with _digest_lock:
            hit = _digest_cache.get(sql)
            if hit is not None:
                _digest_cache.move_to_end(sql)
                return hit
    norm = normalize_sql(sql)
    out = (hashlib.sha256(norm.encode()).hexdigest()[:32], norm)
    if cacheable:
        with _digest_lock:
            _digest_cache[sql] = out
            while len(_digest_cache) > _DIGEST_CACHE_CAP:
                _digest_cache.popitem(last=False)
    return out


def digest_record(sql: str, dur_ns: int, phases: dict | None = None,
                  rows: int = 0, error: str | None = None,
                  op_stats: list[dict] | None = None,
                  mem_bytes: int = 0,
                  tag: str | None = None,
                  trace_id: int | None = None) -> tuple[str, str]:
    """Fold one finished statement into its digest's summary row.
    -> (digest, normalized text) so callers (slow log) can reuse them.
    `tag` disambiguates statements inside a multi-statement batch (the
    parser keeps no per-statement offsets, so all of them share the
    batch text): without it, an INSERT and a SELECT in one batch would
    merge their phases and op stats under a single digest row."""
    dg, norm = sql_digest(sql)
    if tag:
        norm = f"{norm} [{tag}]"
        dg = hashlib.sha256(norm.encode()).hexdigest()[:32]
    now = time.time()
    with _lock:
        rec = _summary.get(dg)
        if rec is None:
            rec = _summary[dg] = {
                "digest": dg, "digest_text": norm[:1024],
                "exec_count": 0, "sum_latency_ns": 0,
                "max_latency_ns": 0, "min_latency_ns": None,
                "sum_parse_ns": 0, "sum_plan_ns": 0, "sum_exec_ns": 0,
                "sum_commit_ns": 0, "sum_rows": 0, "sum_errors": 0,
                "max_mem_bytes": 0,   # peak tracked bytes (memtrack)
                "last_trace_id": 0,   # latest retained trace (trace.py)
                "first_seen": now, "last_seen": now,
                "ops": {},      # op name -> {time_ns, act_rows, device}
            }
        _summary.move_to_end(dg)
        rec["exec_count"] += 1
        rec["sum_latency_ns"] += dur_ns
        rec["max_latency_ns"] = max(rec["max_latency_ns"], dur_ns)
        rec["min_latency_ns"] = dur_ns if rec["min_latency_ns"] is None \
            else min(rec["min_latency_ns"], dur_ns)
        for phase, ns in (phases or {}).items():
            rec["sum_" + phase + "_ns"] = \
                rec.get("sum_" + phase + "_ns", 0) + ns
        rec["sum_rows"] += rows
        if error:
            rec["sum_errors"] += 1
        if mem_bytes > rec.get("max_mem_bytes", 0):
            rec["max_mem_bytes"] = mem_bytes
        if trace_id is not None:
            # a digest hot spot links to its latest concrete timeline
            # (sampled or slow-captured — trace.py retention)
            rec["last_trace_id"] = trace_id
        rec["last_seen"] = now
        for op in op_stats or ():
            agg = rec["ops"].setdefault(
                op["name"], {"time_ns": 0, "act_rows": 0,
                             "device_time_ns": 0})
            agg["time_ns"] += op.get("time_ns", 0)
            agg["act_rows"] += op.get("act_rows", 0)
            agg["device_time_ns"] += op.get("device_time_ns", 0)
        while len(_summary) > SUMMARY_CAP:
            _summary.popitem(last=False)
    return dg, norm


def digest_max_mem(sql: str) -> int:
    """The digest's historical peak tracked bytes (0 when unseen): the
    admission controller's footprint projection — a statement shaped
    like one that peaked at N bytes is assumed to need N again."""
    dg, _norm = sql_digest(sql)
    with _lock:
        rec = _summary.get(dg)
        return rec.get("max_mem_bytes", 0) if rec is not None else 0


def _hot_ops(rec: dict, top: int = 3) -> str:
    """Per-digest operator hot spots, worst first."""
    items = sorted(rec["ops"].items(), key=lambda kv: -kv[1]["time_ns"])
    parts = []
    for name, a in items[:top]:
        s = f"{name} time={a['time_ns'] / 1e6:.2f}ms rows={a['act_rows']}"
        if a["device_time_ns"]:
            s += f" device={a['device_time_ns'] / 1e6:.2f}ms"
        parts.append(s)
    return "; ".join(parts)


def digest_summary() -> list[dict]:
    """Snapshot rows for events_statements_summary_by_digest, hottest
    (by cumulative latency) first."""
    with _lock:
        # per-record deep copy of the ops map: digest_record mutates the
        # live dicts under this same lock, and _hot_ops iterates them
        # after release
        recs = []
        for r in _summary.values():
            c = dict(r)
            c["ops"] = {k: dict(v) for k, v in r["ops"].items()}
            recs.append(c)
    recs.sort(key=lambda r: -r["sum_latency_ns"])
    out = []
    for r in recs:
        out.append({
            "digest": r["digest"], "digest_text": r["digest_text"],
            "exec_count": r["exec_count"],
            "sum_latency_ns": r["sum_latency_ns"],
            "max_latency_ns": r["max_latency_ns"],
            "min_latency_ns": r["min_latency_ns"] or 0,
            "avg_latency_ns": r["sum_latency_ns"] // r["exec_count"],
            "sum_parse_ns": r["sum_parse_ns"],
            "sum_plan_ns": r["sum_plan_ns"],
            "sum_exec_ns": r["sum_exec_ns"],
            "sum_commit_ns": r["sum_commit_ns"],
            "sum_rows": r["sum_rows"], "sum_errors": r["sum_errors"],
            "max_mem_bytes": r.get("max_mem_bytes", 0),
            "last_trace_id": r.get("last_trace_id", 0),
            "first_seen": r["first_seen"], "last_seen": r["last_seen"],
            "top_operators": _hot_ops(r),
        })
    return out


# -- per-digest mode-history memo ------------------------------------------
#
# The optimizer's mode choices (direct vs hash group table, fused vs
# unfused, hybrid engaged, host fallback) are made from *estimates*; this
# memo records what actually ran, per digest and per operator, with the
# observed group cardinality and per-mode device time. It is the read
# side for feedback-driven mode selection (ROADMAP item 3): a planner
# that consults `memo_lookup`-style reads can learn "this digest's
# hashagg always escalates — start at the bigger capacity" without
# re-discovering it per statement. Same LRU discipline as _summary.

_memo_lock = threading.Lock()
# (digest, op name) -> record                    guarded-by: _memo_lock
_memo: "OrderedDict[tuple[str, str], dict]" = OrderedDict()


def memo_record(digest: str, op_stats: list[dict] | None) -> None:
    """Fold one statement's per-operator runtime stats into the memo.
    Only operators that reported a `mode` (i.e. actually chose between
    execution strategies) take a row — scans/sorts without a mode field
    stay out so the table holds decisions, not the whole plan."""
    if not op_stats:
        return
    from tidb_tpu import config
    cap = config.stmt_profile_cap()
    now = time.time()
    with _memo_lock:
        for op in op_stats:
            mode = op.get("mode")
            if not mode:
                continue
            key = (digest, op.get("name", "?"))
            rec = _memo.get(key)
            if rec is None:
                rec = _memo[key] = {
                    "digest": digest, "op": key[1],
                    "runs": 0, "last_mode": "", "last_groups": 0,
                    "max_groups": 0, "first_seen": now, "last_seen": now,
                    "modes": {},   # mode -> {runs, device_ns, rows}
                }
            _memo.move_to_end(key)
            groups = op.get("act_rows", 0)
            rec["runs"] += 1
            rec["last_mode"] = mode
            rec["last_groups"] = groups
            rec["max_groups"] = max(rec["max_groups"], groups)
            rec["last_seen"] = now
            m = rec["modes"].setdefault(
                mode, {"runs": 0, "device_ns": 0, "rows": 0})
            m["runs"] += 1
            m["device_ns"] += op.get("device_time_ns", 0)
            m["rows"] += groups
        while len(_memo) > cap:
            _memo.popitem(last=False)


def memo_snapshot() -> list[dict]:
    """Rows for information_schema.statement_profile, one per
    (digest, operator, mode) — flattened so SQL can filter on mode."""
    with _memo_lock:
        recs = []
        for r in _memo.values():
            recs.append((dict(r), {k: dict(v) for k, v in
                                   r["modes"].items()}))
    out = []
    for rec, modes in recs:
        for mode, m in modes.items():
            out.append({
                "digest": rec["digest"], "op": rec["op"], "mode": mode,
                "runs": m["runs"], "device_ns": m["device_ns"],
                "rows": m["rows"],
                "last_mode": rec["last_mode"],
                "last_groups": rec["last_groups"],
                "max_groups": rec["max_groups"],
                "last_seen": rec["last_seen"],
            })
    out.sort(key=lambda r: (-r["device_ns"], r["digest"], r["op"]))
    return out


def memo_reset() -> None:
    with _memo_lock:
        _memo.clear()


def reset() -> None:
    """Test hook."""
    global _event_seq
    with _lock:
        _history.clear()
        _current.clear()
        _summary.clear()
        _event_seq = 0
    memo_reset()
