"""PERFORMANCE_SCHEMA statement events.

Reference: /root/reference/perfschema/const.go:120-298 — the
events_statements_current / events_statements_history virtual tables.
Process-wide: a per-session current-event slot plus a bounded history
ring; every non-internal statement records its SQL, wall time, phase
breakdown (parse/plan/execute/commit, from the trace span tree), row
count and error state. Served as memtables by the planner, exactly like
INFORMATION_SCHEMA."""

from __future__ import annotations

import threading
import time
from collections import deque

__all__ = ["stmt_begin", "stmt_end", "current_events", "history_events",
           "HISTORY_CAP"]

HISTORY_CAP = 1024

_lock = threading.Lock()
_history: deque = deque(maxlen=HISTORY_CAP)
_current: dict[int, dict] = {}       # session_id -> live event
_event_seq = 0


def stmt_begin(session_id: int, sql: str) -> dict:
    global _event_seq
    with _lock:
        _event_seq += 1
        ev = {
            "thread_id": session_id,
            "event_id": _event_seq,
            "sql_text": sql[:1024],
            "state": "running",
            "timer_start_us": int(time.time() * 1e6),
            "timer_wait_ns": 0,
            "parse_ns": 0, "plan_ns": 0, "exec_ns": 0, "commit_ns": 0,
            "rows": 0,
            "error": None,
        }
        _current[session_id] = ev
        return ev


def stmt_end(ev: dict, root=None, rows: int = 0,
             error: str | None = None) -> None:
    from tidb_tpu import trace
    with _lock:
        ev["state"] = "error" if error else "completed"
        ev["error"] = error and error[:256]
        ev["rows"] = rows
        if root is not None:
            ev["timer_wait_ns"] = root.duration_ns
            for phase in ("parse", "plan", "execute", "commit"):
                key = ("exec" if phase == "execute" else phase) + "_ns"
                ev[key] = trace.phase_ns(root, phase)
        _history.append(dict(ev))


def session_closed(session_id: int) -> None:
    with _lock:
        _current.pop(session_id, None)


def current_events() -> list[dict]:
    with _lock:
        return [dict(ev) for _sid, ev in sorted(_current.items())]


def history_events() -> list[dict]:
    with _lock:
        return [dict(ev) for ev in _history]


def reset() -> None:
    """Test hook."""
    global _event_seq
    with _lock:
        _history.clear()
        _current.clear()
        _event_seq = 0
