"""Lease-based owner election over the KV store.

Reference: /root/reference/owner/manager.go:40-53 — etcd-session
campaigns electing the DDL owner (and stats owner). There is no etcd
here; the shared MVCC store itself is the coordination substrate, the
same move the reference's GC worker makes with its mysql.tidb lease rows
(gc_worker.go:550 checkLeader). A lease record holds (owner_id,
expiry_ts); campaign() atomically takes over expired/absent leases via
an ordinary 2PC write, so exactly one campaigner per key wins — a
conflicting writer hits WriteConflictError and loses.
"""

from __future__ import annotations

import json
import time
import uuid

from tidb_tpu import kv
from tidb_tpu.mockstore.rpc import TimeoutError_

__all__ = ["OwnerManager", "DDL_OWNER_KEY"]

DDL_OWNER_KEY = b"m_owner_ddl"


class OwnerManager:
    """One election participant (ref: owner.Manager)."""

    def __init__(self, storage, key: bytes = DDL_OWNER_KEY,
                 lease_ms: int = 2000, owner_id: str | None = None):
        self.storage = storage
        self.key = key
        self.lease_ms = lease_ms
        self.id = owner_id or uuid.uuid4().hex[:12]

    def _read(self, txn):
        raw = txn.get(self.key)
        if not raw:
            return "", 0
        try:
            o = json.loads(raw)
            return o["id"], int(o["expiry"])
        except (ValueError, KeyError):
            return "", 0     # corrupt lease: treated as expired

    def campaign(self) -> bool:
        """Take or renew the lease; True iff this manager is now owner."""
        now = int(time.time() * 1000)
        txn = self.storage.begin()
        try:
            owner, expiry = self._read(txn)
            if owner == self.id or not owner or expiry <= now:
                txn.set(self.key, json.dumps(
                    {"id": self.id,
                     "expiry": now + self.lease_ms}).encode())
                txn.commit()
                return True
            txn.rollback()
            return False
        except (kv.RetryableError, TimeoutError_):
            # lost the race to another campaigner, or the commit RPC
            # timed out (fleet mode: store plane over the wire) —
            # either way this round is lost; the next campaign retries
            return False
        except Exception:
            if getattr(txn, "valid", False):
                txn.rollback()
            raise

    def is_owner(self) -> bool:
        """Currently holding an unexpired lease (no renewal)."""
        now = int(time.time() * 1000)
        txn = self.storage.begin()
        try:
            owner, expiry = self._read(txn)
            return owner == self.id and expiry > now
        finally:
            txn.rollback()

    def owner_id(self) -> str | None:
        """The current (unexpired) owner, or None."""
        now = int(time.time() * 1000)
        txn = self.storage.begin()
        try:
            owner, expiry = self._read(txn)
            return owner if owner and expiry > now else None
        finally:
            txn.rollback()

    def resign(self) -> None:
        txn = self.storage.begin()
        try:
            owner, _ = self._read(txn)
            if owner == self.id:
                txn.delete(self.key)
                txn.commit()
            else:
                txn.rollback()
        except (kv.RetryableError, TimeoutError_):
            pass
        except Exception:
            if getattr(txn, "valid", False):
                txn.rollback()
            raise
