"""Privilege subsystem: grant tables, in-memory cache, auth verification.

Reference: /root/reference/privilege/privileges/ — grant tables loaded
into an in-memory MySQLPrivilege cache (cache.go:104-112,581),
RequestVerification checks (privileges.go:56), reload on grant
notification. Design deviation (documented): instead of per-privilege
Y/N enum columns, grants are a BIGINT bitmask per (user, host[, db[,
table]]) row — identical semantics, columnar-friendly storage.

Auth is mysql_native_password (ref: util/auth/auth.go):
    stored  = SHA1(SHA1(password))                    ("*HEX" in the table)
    client sends scramble = SHA1(pwd) XOR SHA1(salt + stored)
    server recovers SHA1(pwd) and checks SHA1(of it) == stored.
"""

from __future__ import annotations

import hashlib
import threading

__all__ = ["Priv", "ALL_PRIVS", "PrivilegeCache", "encode_password",
           "check_scramble", "PRIV_BY_NAME"]


class Priv:
    SELECT = 1 << 0
    INSERT = 1 << 1
    UPDATE = 1 << 2
    DELETE = 1 << 3
    CREATE = 1 << 4
    DROP = 1 << 5
    ALTER = 1 << 6
    INDEX = 1 << 7
    CREATE_USER = 1 << 8
    GRANT = 1 << 9
    SUPER = 1 << 10          # SET GLOBAL etc. (system administration)


ALL_PRIVS = (Priv.SELECT | Priv.INSERT | Priv.UPDATE | Priv.DELETE |
             Priv.CREATE | Priv.DROP | Priv.ALTER | Priv.INDEX |
             Priv.CREATE_USER | Priv.GRANT | Priv.SUPER)

PRIV_BY_NAME = {"SELECT": Priv.SELECT, "INSERT": Priv.INSERT,
                "UPDATE": Priv.UPDATE, "DELETE": Priv.DELETE,
                "CREATE": Priv.CREATE, "DROP": Priv.DROP,
                "ALTER": Priv.ALTER, "INDEX": Priv.INDEX,
                "SUPER": Priv.SUPER, "GRANT": Priv.GRANT,
                "CREATE USER": Priv.CREATE_USER,
                "ALL": ALL_PRIVS}


def encode_password(password: str) -> str:
    """PASSWORD(): '*' + hex(SHA1(SHA1(pw))), empty pw -> ''."""
    if not password:
        return ""
    h = hashlib.sha1(hashlib.sha1(password.encode()).digest()).hexdigest()
    return "*" + h.upper()


def check_scramble(auth_response: bytes, salt: bytes, stored: str) -> bool:
    """Verify a mysql_native_password scramble against the stored hash."""
    if not stored:
        return not auth_response        # empty password: empty response
    if len(auth_response) != 20 or not stored.startswith("*"):
        return False
    stage2 = bytes.fromhex(stored[1:])
    mask = hashlib.sha1(salt + stage2).digest()
    sha1_pwd = bytes(a ^ b for a, b in zip(auth_response, mask))
    return hashlib.sha1(sha1_pwd).digest() == stage2


_LOOPBACK = {"localhost", "127.0.0.1", "::1"}


def _host_match(pattern: str, host: str) -> bool:
    if pattern == "%" or pattern == host:
        return True
    # loopback aliases are interchangeable (a 'u'@'localhost' account must
    # authenticate from 127.0.0.1 TCP connections, as in MySQL)
    return pattern in _LOOPBACK and host in _LOOPBACK


class PrivilegeCache:
    """Grant tables snapshot, reloaded on version bump (GRANT/REVOKE/
    CREATE USER notify via `invalidate`). Ref: privileges/cache.go."""

    def __init__(self, storage):
        self.storage = storage
        self._mu = threading.Lock()
        self._loaded = False
        # (user,) -> [(host, auth_string, privs)]
        self._users: dict[str, list] = {}
        # (user, db) matching is by row scan: [(user, host, db, privs)]
        self._dbs: list = []
        self._tables: list = []       # [(user, host, db, tbl, privs)]

    def invalidate(self) -> None:
        with self._mu:
            self._loaded = False

    def _session(self):
        from tidb_tpu.session import Session
        return Session(self.storage, db="mysql", internal=True)

    def _load_locked(self) -> None:
        users: dict[str, list] = {}
        dbs: list = []
        tables: list = []
        s = self._session()
        try:
            if not s.domain.info_schema().has_db("mysql"):
                self._users, self._dbs, self._tables = {}, [], []
                self._loaded = True
                return
            for host, user, auth, privs in s.query(
                    "SELECT host, user, authentication_string, privs "
                    "FROM mysql.user").rows:
                users.setdefault(user, []).append(
                    (host, auth or "", int(privs)))
            for host, user, db, privs in s.query(
                    "SELECT host, user, db, privs FROM mysql.db").rows:
                dbs.append((user, host, db, int(privs)))
            for host, user, db, tbl, privs in s.query(
                    "SELECT host, user, db, table_name, privs "
                    "FROM mysql.tables_priv").rows:
                tables.append((user, host, db, tbl, int(privs)))
        finally:
            s.close()
        self._users, self._dbs, self._tables = users, dbs, tables
        self._loaded = True

    def _ensure(self) -> None:
        with self._mu:
            if not self._loaded:
                self._load_locked()

    # -- connection auth (ref: privileges.go ConnectionVerification) --------

    def connection_verify(self, user: str, host: str, auth_response: bytes,
                          salt: bytes) -> bool:
        self._ensure()
        for pat, stored, _p in self._users.get(user, ()):
            if _host_match(pat, host) and \
                    check_scramble(auth_response, salt, stored):
                return True
        return False

    # -- statement checks (ref: privileges.go RequestVerification) ----------

    def effective_privs(self, user: str, host: str, db: str,
                        table: str) -> int:
        self._ensure()
        privs = 0
        for pat, _a, p in self._users.get(user, ()):
            if _host_match(pat, host):
                privs |= p
        for u, pat, d, p in self._dbs:
            if u == user and _host_match(pat, host) and d == db:
                privs |= p
        for u, pat, d, t, p in self._tables:
            if u == user and _host_match(pat, host) and d == db and \
                    t == table:
                privs |= p
        return privs

    def describe_grants(self, user: str,
                        host: str | None = None) -> list[str]:
        """GRANT statements reconstructing one ACCOUNT's privileges
        (ref: privileges.go ShowGrants). host filters to that exact host
        pattern; None lists every host variant of the name."""
        self._ensure()

        def want(pat: str) -> bool:
            return host is None or pat == host

        def names(p: int) -> str:
            if p & ALL_PRIVS == ALL_PRIVS:
                return "ALL PRIVILEGES"
            display = dict(PRIV_BY_NAME)
            display.pop("ALL", None)
            # bits with multi-word display names (not in the GRANT-able
            # name map)
            display["CREATE USER"] = Priv.CREATE_USER
            display["GRANT OPTION"] = Priv.GRANT
            got = [n for n, bit in display.items() if p & bit]
            return ", ".join(got) if got else "USAGE"

        out = []
        for pat, _a, p in self._users.get(user, ()):
            if want(pat):
                out.append(
                    f"GRANT {names(p)} ON *.* TO '{user}'@'{pat}'")
        for u, pat, d, p in self._dbs:
            if u == user and want(pat):
                out.append(
                    f"GRANT {names(p)} ON `{d}`.* TO '{user}'@'{pat}'")
        for u, pat, d, t, p in self._tables:
            if u == user and want(pat):
                out.append(f"GRANT {names(p)} ON `{d}`.`{t}` "
                           f"TO '{user}'@'{pat}'")
        return out

    def request_verification(self, user: str, host: str, db: str,
                             table: str, want: int) -> bool:
        return (self.effective_privs(user, host, db, table) & want) == want
