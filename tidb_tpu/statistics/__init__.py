"""CBO statistics: equi-depth histograms, count-min sketch, selectivity.

Reference: /root/reference/statistics/ — Histogram (histogram.go:39),
CMSketch (cmsketch.go:30), table stats (table.go:46), Handle with
lease-based reload (handle.go:32,106), session delta collection
(update.go:53), selectivity estimation (selectivity.go:30).

TPU-first recast: the reference builds histograms by merging per-region
sample collectors row-at-a-time. Here ANALYZE scans the table through the
normal coprocessor path into columnar chunks and builds each histogram
from a whole-column sort — on device (jnp.sort, ops/stats.py) for large
numeric columns, numpy otherwise. Estimation stays host-side: the planner
is host control-plane code.

Persistence follows the reference's mysql.stats_* tables in spirit: stats
serialize to one JSON blob per table under a meta key (m_stats/<id>), so
a fresh Domain on the same store recovers them (handle.Update analogue).
"""

from __future__ import annotations

import hashlib
import json
import math
import threading
from bisect import bisect_left
from dataclasses import dataclass, field

import numpy as np

from tidb_tpu import codec, ranger, tablecodec
from tidb_tpu.schema.model import IndexInfo, TableInfo
from tidb_tpu.sqltypes import EvalType

__all__ = ["Histogram", "CMSketch", "ColumnStats", "IndexStats",
           "TableStats", "StatsHandle", "build_histogram",
           "build_column_stats", "analyze_table", "selectivity",
           "cm_key", "PSEUDO_ROW_COUNT", "SELECTION_FACTOR"]

# Pseudo-stats rates; ref: statistics/table.go pseudo estimation constants.
PSEUDO_ROW_COUNT = 10000
PSEUDO_EQUAL_RATE = 1000     # eq selects 1/1000
PSEUDO_LESS_RATE = 3         # < selects 1/3
PSEUDO_BETWEEN_RATE = 40     # between selects 1/40
SELECTION_FACTOR = 0.8       # default filter selectivity (plan/task.go)

DEFAULT_BUCKETS = 256
CM_DEPTH = 4
CM_WIDTH = 2048
MAX_SAMPLE = 100_000         # index-key encoding sample cap


# ---------------------------------------------------------------------------
# value domain: histogram bounds must be comparable + interpolatable.
# Numeric columns use float keys; strings/bytes use their raw value with
# byte-prefix interpolation.


def _bytes_frac(v: bytes, lo: bytes, hi: bytes) -> float:
    """Position of v in [lo, hi) by 8-byte window after the common prefix."""
    p = 0
    while p < len(lo) and p < len(hi) and lo[p] == hi[p]:
        p += 1

    def win(b: bytes) -> int:
        w = b[p:p + 8].ljust(8, b"\0")
        return int.from_bytes(w, "big")

    lo_i, hi_i, v_i = win(lo), win(hi), win(v)
    if hi_i <= lo_i:
        return 0.5
    return min(1.0, max(0.0, (v_i - lo_i) / (hi_i - lo_i)))


def _interp(v, lo, hi) -> float:
    """Fraction of [lo, hi) below v."""
    if isinstance(v, (bytes, bytearray)):
        return _bytes_frac(bytes(v), bytes(lo), bytes(hi))
    if isinstance(v, str):
        return _bytes_frac(v.encode("utf-8", "surrogateescape"),
                           str(lo).encode("utf-8", "surrogateescape"),
                           str(hi).encode("utf-8", "surrogateescape"))
    try:
        lo_f, hi_f, v_f = float(lo), float(hi), float(v)
    except (TypeError, ValueError):
        return 0.5
    if hi_f <= lo_f:
        return 0.5
    return min(1.0, max(0.0, (v_f - lo_f) / (hi_f - lo_f)))


@dataclass
class Histogram:
    """Equi-depth histogram (ref: statistics/histogram.go:39). Buckets are
    parallel lists; counts are cumulative row counts through each bucket;
    repeats count occurrences of each bucket's upper bound."""

    ndv: int = 0
    null_count: int = 0
    total: int = 0
    lowers: list = field(default_factory=list)
    uppers: list = field(default_factory=list)
    counts: list = field(default_factory=list)    # cumulative
    repeats: list = field(default_factory=list)

    @property
    def num_buckets(self) -> int:
        return len(self.uppers)

    def _bucket_count(self, i: int) -> int:
        return self.counts[i] - (self.counts[i - 1] if i else 0)

    def _locate(self, v) -> int:
        """First bucket whose upper >= v (may be num_buckets)."""
        return bisect_left(self.uppers, v)

    def less_row_count(self, v) -> float:
        """Estimated rows strictly < v (ref: histogram.go lessRowCount)."""
        if not self.uppers:
            return 0.0
        i = self._locate(v)
        if i >= self.num_buckets:
            return float(self.total)
        prev = self.counts[i - 1] if i else 0
        if v <= self.lowers[i]:
            return float(prev)
        in_bucket = self._bucket_count(i) - self.repeats[i]
        frac = _interp(v, self.lowers[i], self.uppers[i])
        return prev + frac * in_bucket

    def equal_row_count(self, v) -> float:
        if not self.uppers or self.ndv == 0:
            return 0.0
        if v < self.lowers[0] or v > self.uppers[-1]:
            return 0.0
        i = self._locate(v)
        if i < self.num_buckets and v == self.uppers[i]:
            return float(self.repeats[i])
        return self.total / self.ndv

    def between_row_count(self, lo, hi, lo_incl: bool = True,
                          hi_incl: bool = False) -> float:
        """Estimated rows in the interval; None bound = unbounded."""
        lo_cnt = 0.0 if lo is None else self.less_row_count(lo)
        hi_cnt = float(self.total) if hi is None else self.less_row_count(hi)
        est = hi_cnt - lo_cnt
        if lo is not None and not lo_incl:
            est -= self.equal_row_count(lo)
        if hi is not None and hi_incl:
            est += self.equal_row_count(hi)
        return max(0.0, min(float(self.total), est))

    # -- serialization -------------------------------------------------------

    def to_obj(self):
        return {"ndv": self.ndv, "null": self.null_count, "total": self.total,
                "lowers": [_val_to_obj(v) for v in self.lowers],
                "uppers": [_val_to_obj(v) for v in self.uppers],
                "counts": self.counts, "repeats": self.repeats}

    @staticmethod
    def from_obj(o) -> "Histogram":
        return Histogram(ndv=o["ndv"], null_count=o["null"],
                         total=o["total"],
                         lowers=[_val_from_obj(v) for v in o["lowers"]],
                         uppers=[_val_from_obj(v) for v in o["uppers"]],
                         counts=list(o["counts"]),
                         repeats=list(o["repeats"]))


def _val_to_obj(v):
    if isinstance(v, (bytes, bytearray)):
        import base64
        return {"b": base64.b64encode(bytes(v)).decode()}
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    return v


def _val_from_obj(o):
    if isinstance(o, dict) and "b" in o:
        import base64
        return base64.b64decode(o["b"])
    return o


def build_histogram(values, counts, n_buckets: int = DEFAULT_BUCKETS,
                    null_count: int = 0) -> Histogram:
    """Build from distinct `values` (ascending) with per-value `counts`."""
    h = Histogram(ndv=len(values), null_count=null_count)
    if len(values) == 0:
        return h
    total = int(sum(counts))
    per_bucket = max(1, math.ceil(total / n_buckets))
    cum = 0
    cur = 0  # rows in current bucket
    for v, c in zip(values, counts):
        c = int(c)
        if cur > 0 and cur + c > per_bucket:
            cur = 0
        if cur == 0:
            h.lowers.append(v)
            h.uppers.append(v)
            h.counts.append(cum)
            h.repeats.append(0)
        cum += c
        cur += c
        h.uppers[-1] = v
        h.counts[-1] = cum
        h.repeats[-1] = c
    h.total = cum
    return h


class CMSketch:
    """Count-min sketch for point frequency (ref: statistics/cmsketch.go:30).
    Inserted per *distinct* value with its count (we see the whole column at
    ANALYZE time, unlike the reference's streaming sampler)."""

    def __init__(self, depth: int = CM_DEPTH, width: int = CM_WIDTH):
        self.depth = depth
        self.width = width
        self.count = 0
        self.table = np.zeros((depth, width), dtype=np.int64)

    def _positions(self, key: bytes) -> list[int]:
        d = hashlib.blake2b(key, digest_size=16).digest()
        h1 = int.from_bytes(d[:8], "little")
        h2 = int.from_bytes(d[8:], "little")
        return [(h1 + i * h2) % self.width for i in range(self.depth)]

    def insert(self, key: bytes, cnt: int = 1) -> None:
        self.count += cnt
        for i, p in enumerate(self._positions(key)):
            self.table[i, p] += cnt

    def query(self, key: bytes) -> int:
        vals = [int(self.table[i, p])
                for i, p in enumerate(self._positions(key))]
        return min(vals)

    def to_obj(self):
        import base64
        return {"depth": self.depth, "width": self.width, "count": self.count,
                "table": base64.b64encode(
                    self.table.astype("<i8").tobytes()).decode()}

    @staticmethod
    def from_obj(o) -> "CMSketch":
        import base64
        cm = CMSketch(o["depth"], o["width"])
        cm.count = o["count"]
        cm.table = np.frombuffer(
            base64.b64decode(o["table"]), dtype="<i8").reshape(
                o["depth"], o["width"]).copy()
        return cm


def _cm_key(v) -> bytes:
    if isinstance(v, (bytes, bytearray)):
        return bytes(v)
    if isinstance(v, str):
        return b"s" + v.encode("utf-8", "surrogateescape")
    if isinstance(v, (int, np.integer)):
        return b"i" + int(v).to_bytes(8, "little", signed=True)
    return b"f" + np.float64(v).tobytes()


def cm_key(v) -> bytes:
    """Public CMSketch key encoding for a column value — external
    consumers (the hybrid join's heavy-hitter seeding) must query with
    EXACTLY the encoding ANALYZE inserted with."""
    return _cm_key(v)


@dataclass
class ColumnStats:
    hist: Histogram
    cms: CMSketch | None = None

    def equal_count(self, v) -> float:
        if self.cms is not None:
            return float(self.cms.query(_cm_key(v)))
        return self.hist.equal_row_count(v)


@dataclass
class IndexStats:
    """Histogram over memcomparable-encoded index keys: multi-column range
    estimation reduces to a byte-range query (the reference keeps index
    hists over encoded keys too, statistics/histogram.go index path)."""

    hist: Histogram
    cms: CMSketch | None = None

    def ranges_row_count(self, index_ranges) -> float:
        """index_ranges: KVRange list with the index prefix stripped."""
        total = 0.0
        for r in index_ranges:
            total += self.hist.between_row_count(r.start, r.end)
        return total


@dataclass
class TableStats:
    """Per-table stats (ref: statistics/table.go:46)."""

    table_id: int
    version: int = 0            # analyze ts
    count: int = PSEUDO_ROW_COUNT
    modify_count: int = 0
    columns: dict = field(default_factory=dict)   # col_id -> ColumnStats
    indexes: dict = field(default_factory=dict)   # idx_id -> IndexStats
    pseudo: bool = True

    # -- estimation ----------------------------------------------------------

    def col_ranges_row_count(self, col_id: int,
                             ranges: list[ranger.DatumRange]) -> float:
        cs = self.columns.get(col_id)
        total = 0.0
        for r in ranges:
            lo = r.low[0] if r.low and not r.low_unbounded else None
            hi = r.high[0] if r.high and not r.high_unbounded else None
            # IS NULL point range ([None],[None]): answered by null_count,
            # not the histogram (NULLs are excluded from it)
            if lo is None and hi is None and r.low and r.high and \
                    not r.low_unbounded and not r.high_unbounded:
                if cs is None or self.pseudo:
                    total += self.count / PSEUDO_EQUAL_RATE
                else:
                    total += float(cs.hist.null_count)
                continue
            # decimal datums are (frac, scaled) with the column's frac;
            # column histograms store the scaled int (the chunk layout)
            if isinstance(lo, tuple):
                lo = lo[1]
            if isinstance(hi, tuple):
                hi = hi[1]
            if cs is None or self.pseudo:
                total += self._pseudo_range(lo, hi)
                continue
            try:
                if lo is not None and lo == hi and r.low_incl and \
                        r.high_incl:
                    total += cs.equal_count(lo)
                else:
                    total += cs.hist.between_row_count(
                        lo, hi, r.low_incl, r.high_incl)
            except TypeError:   # incomparable datum vs histogram domain
                total += self._pseudo_range(lo, hi)
        return min(float(self.count), total)

    def index_ranges_row_count(self, idx: IndexInfo,
                               ranges: list[ranger.DatumRange]) -> float:
        st = self.indexes.get(idx.id)
        if st is not None and not self.pseudo:
            kvr = ranger.index_ranges_to_kv(0, 0, ranges)
            strip = len(tablecodec.index_prefix(0, 0))
            stripped = [type(r)(r.start[strip:], r.end[strip:]) for r in kvr]
            return min(float(self.count), st.ranges_row_count(stripped))
        total = 0.0
        for r in ranges:
            sel = 1.0
            for i in range(max(len(r.low), len(r.high))):
                lo = r.low[i] if i < len(r.low) else None
                hi = r.high[i] if i < len(r.high) else None
                sel *= self._pseudo_range(lo, hi) / max(1, self.count)
            total += sel * self.count
        return min(float(self.count), total)

    def _pseudo_range(self, lo, hi) -> float:
        if lo is not None and lo == hi:
            return self.count / PSEUDO_EQUAL_RATE
        if lo is not None and hi is not None:
            return self.count / PSEUDO_BETWEEN_RATE
        if lo is None and hi is None:
            return float(self.count)
        return self.count / PSEUDO_LESS_RATE

    # -- serialization -------------------------------------------------------

    def to_blob(self) -> bytes:
        o = {"table_id": self.table_id, "version": self.version,
             "count": self.count, "modify_count": self.modify_count,
             "columns": {str(k): {"hist": v.hist.to_obj(),
                                  "cms": v.cms.to_obj() if v.cms else None}
                         for k, v in self.columns.items()},
             "indexes": {str(k): {"hist": v.hist.to_obj(),
                                  "cms": v.cms.to_obj() if v.cms else None}
                         for k, v in self.indexes.items()}}
        return json.dumps(o).encode()

    @staticmethod
    def from_blob(blob: bytes) -> "TableStats":
        o = json.loads(blob)
        ts = TableStats(table_id=o["table_id"], version=o["version"],
                        count=o["count"], modify_count=o["modify_count"],
                        pseudo=False)
        for k, v in o["columns"].items():
            ts.columns[int(k)] = ColumnStats(
                Histogram.from_obj(v["hist"]),
                CMSketch.from_obj(v["cms"]) if v["cms"] else None)
        for k, v in o["indexes"].items():
            ts.indexes[int(k)] = IndexStats(
                Histogram.from_obj(v["hist"]),
                CMSketch.from_obj(v["cms"]) if v["cms"] else None)
        return ts


# ---------------------------------------------------------------------------
# building stats from data


def _distinct_sorted(col) -> tuple[list, np.ndarray, int]:
    """(distinct values asc, counts, null_count) from a chunk Column."""
    valid = np.asarray(col.valid)
    null_count = int((~valid).sum())
    data = col.data[valid] if null_count else col.data
    if len(data) == 0:
        return [], np.empty(0, np.int64), null_count
    if data.dtype == np.dtype(object):   # strings: python sort
        vals: dict = {}
        for v in data:
            vals[v] = vals.get(v, 0) + 1
        keys = sorted(vals)
        return keys, np.array([vals[k] for k in keys], np.int64), null_count
    s = _device_sort(np.ascontiguousarray(data))
    edge = np.flatnonzero(s[1:] != s[:-1])
    starts = np.concatenate(([0], edge + 1))
    counts = np.diff(np.concatenate((starts, [len(s)])))
    return list(s[starts]), counts, null_count


_DEVICE_SORT_MIN = 1 << 17


def _device_sort(data: np.ndarray) -> np.ndarray:
    """Whole-column sort — the ANALYZE hot loop. Large numeric columns sort
    on the accelerator (one fused XLA sort), small ones on host."""
    if len(data) >= _DEVICE_SORT_MIN and data.dtype in (
            np.dtype(np.int64), np.dtype(np.float64),
            np.dtype(np.int32), np.dtype(np.float32)):
        from tidb_tpu.ops.stats import device_sort
        return device_sort(data)
    return np.sort(data, kind="stable")


def build_column_stats(col, n_buckets: int = DEFAULT_BUCKETS) -> ColumnStats:
    vals, counts, nulls = _distinct_sorted(col)
    hist = build_histogram(vals, counts, n_buckets, null_count=nulls)
    cms = CMSketch()
    for v, c in zip(vals, counts):
        cms.insert(_cm_key(v), int(c))
    return ColumnStats(hist, cms)


def _kv_datum(col, row: int):
    """Raw chunk value -> KV-layer datum matching what ranger's
    _exact_datum produces for plan-time range bounds: ints/floats as
    Python scalars, decimals as (column_frac, scaled), strings as-is."""
    if not col.valid[row]:
        return None
    v = col.data[row]
    et = col.ft.eval_type
    if et == EvalType.DECIMAL:
        return (col.ft.frac, int(v))
    if et in (EvalType.INT, EvalType.DATETIME):
        return int(v)
    if et == EvalType.REAL:
        return float(v)
    return v


def _index_key_stats(chunk_cols_rows, n_buckets: int) -> IndexStats:
    """chunk_cols_rows: iterable of per-row datum tuples for the index
    columns (kv-layer values)."""
    vals: dict = {}
    for row in chunk_cols_rows:
        try:
            k = codec.encode_key(row)
        except Exception:
            continue
        vals[k] = vals.get(k, 0) + 1
    keys = sorted(vals)
    counts = np.array([vals[k] for k in keys], np.int64) if keys \
        else np.empty(0, np.int64)
    hist = build_histogram(keys, counts, n_buckets)
    cms = CMSketch()
    for k in keys:
        cms.insert(k, int(vals[k]))
    return IndexStats(hist, cms)


def analyze_table(storage, read_ts: int, info: TableInfo,
                  n_buckets: int = DEFAULT_BUCKETS) -> TableStats:
    """Full-scan ANALYZE (ref: executor/analyze.go:42 AnalyzeExec; sample
    collection mocktikv/analyze.go). Reads the table through the normal
    coprocessor fan-out, then builds per-column and per-index stats."""
    from tidb_tpu.executor import ExecContext, TableReaderExec
    from tidb_tpu.plan.physical import CopPlan, PhysTableReader
    from tidb_tpu.plan.resolver import PlanSchema, SchemaCol

    cols = info.public_columns()
    schema = PlanSchema([SchemaCol(c.name, info.name.lower(), c.ft)
                         for c in cols])
    cop = CopPlan(table=info, cols=list(cols))
    reader = TableReaderExec(PhysTableReader(schema=schema, cop=cop))
    ctx = ExecContext(storage, read_ts, None)

    parts = []
    total = 0
    for ch in reader.chunks(ctx):
        parts.append(ch)
        total += ch.num_rows

    ts = TableStats(table_id=info.id, version=read_ts, count=total,
                    pseudo=False)
    from tidb_tpu.chunk import Column
    for ci, cinfo in enumerate(cols):
        # concatenate once, one whole-column sort (device for big numerics)
        if parts:
            whole = Column(
                cinfo.ft,
                np.concatenate([ch.columns[ci].data for ch in parts]),
                np.concatenate([np.asarray(ch.columns[ci].valid)
                                for ch in parts]))
        else:
            whole = Column.empty(cinfo.ft)
        vals, counts, nulls = _distinct_sorted(whole)
        keys = [v.item() if hasattr(v, "item") else v for v in vals]
        hist = build_histogram(keys, counts, n_buckets, null_count=nulls)
        cms = CMSketch()
        for k, c in zip(keys, counts):
            cms.insert(_cm_key(k), int(c))
        ts.columns[cinfo.id] = ColumnStats(hist, cms)

    # index stats over encoded keys (sampled above MAX_SAMPLE rows)
    from tidb_tpu.schema.model import SchemaState
    name_to_off = {c.name.lower(): i for i, c in enumerate(cols)}
    for idx in info.indexes:
        if idx.state != SchemaState.PUBLIC:
            continue
        offs = [name_to_off[c.lower()] for c in idx.columns
                if c.lower() in name_to_off]
        if len(offs) != len(idx.columns):
            continue
        step = max(1, total // MAX_SAMPLE)

        def rows():
            for ch in parts:
                ccols = [ch.columns[o] for o in offs]
                for r in range(0, ch.num_rows, step):
                    yield tuple(_kv_datum(c, r) for c in ccols)

        st = _index_key_stats(rows(), n_buckets)
        if step > 1:   # scale sampled counts back to table size
            st.hist.total *= step
            st.hist.counts = [c * step for c in st.hist.counts]
            st.hist.repeats = [c * step for c in st.hist.repeats]
            if st.cms is not None:
                st.cms.table *= step
                st.cms.count *= step
        ts.indexes[idx.id] = st
    return ts


# ---------------------------------------------------------------------------
# selectivity


def _expr_col_offsets(e) -> set:
    return e.columns_used()


def selectivity(ts: TableStats, conjuncts, schema_cols, info: TableInfo
                ) -> float:
    """Combined selectivity of the conjuncts (ref: selectivity.go:30).
    Single-column conjuncts estimate through that column's histogram via
    ranger; the rest contribute the default SELECTION_FACTOR each
    (capped), combined under independence."""
    if not conjuncts:
        return 1.0
    count = max(1, ts.count)
    name_to_col = {c.name.lower(): c for c in info.columns}
    sel = 1.0
    defaults = 0
    for e in conjuncts:
        offs = _expr_col_offsets(e)
        done = False
        if len(offs) == 1:
            off = next(iter(offs))
            if off < len(schema_cols):
                sc = schema_cols[off]
                cinfo = name_to_col.get(sc.name.lower())
                if cinfo is not None:
                    path = ranger.detach_index_conditions(
                        [e], [off], [sc.ft])
                    if path.useful and path.ranges is not None:
                        rows = ts.col_ranges_row_count(cinfo.id, path.ranges)
                        sel *= max(rows, 0.0) / count
                        done = True
        if not done:
            defaults += 1
    sel *= SELECTION_FACTOR ** min(defaults, 3)
    return max(sel, 1.0 / count)


# ---------------------------------------------------------------------------
# handle


_STATS_PREFIX = b"m_stats/"


def _stats_key(table_id: int) -> bytes:
    return _STATS_PREFIX + b"%020d" % table_id


class StatsHandle:
    """Stats cache + persistence + DML delta collection (ref:
    statistics/handle.go:32; update.go:53 SessionStatsCollector)."""

    AUTO_ANALYZE_RATIO = 0.5

    def __init__(self, storage):
        self.storage = storage
        self._cache: dict[int, TableStats] = {}
        self._deltas: dict[int, int] = {}
        self.version = 0     # bumped on save/drop; part of plan-cache keys
        # serializes histogram feedback writers (executor threads)
        self._fb_mu = threading.Lock()

    def get(self, table_id: int) -> TableStats:
        ts = self._cache.get(table_id)
        if ts is None:
            ts = self._load(table_id)
            if ts is None:
                ts = TableStats(table_id=table_id)
            self._cache[table_id] = ts
        return ts

    def modify_count(self, table_id: int) -> int:
        """Persisted modify count plus this handle's pending DML delta."""
        return self.get(table_id).modify_count + \
            self._deltas.get(table_id, 0)

    def _load(self, table_id: int) -> TableStats | None:
        txn = self.storage.begin()
        try:
            raw = txn.get(_stats_key(table_id))
            return TableStats.from_blob(raw) if raw else None
        finally:
            txn.rollback()

    def save(self, ts: TableStats) -> None:
        txn = self.storage.begin()
        try:
            txn.set(_stats_key(ts.table_id), ts.to_blob())
            txn.commit()
        except Exception:
            txn.rollback()
            raise
        self._deltas.pop(ts.table_id, None)
        self._cache[ts.table_id] = ts
        self.version += 1

    def drop(self, table_id: int) -> None:
        txn = self.storage.begin()
        try:
            txn.delete(_stats_key(table_id))
            txn.commit()
        except Exception:
            txn.rollback()
            raise
        self._cache.pop(table_id, None)
        self._deltas.pop(table_id, None)
        self.version += 1

    def invalidate(self) -> None:
        self._cache.clear()

    # -- DML deltas ----------------------------------------------------------

    def note_dml(self, table_id: int, modified: int) -> None:
        if modified:
            self._deltas[table_id] = self._deltas.get(table_id, 0) + modified

    def need_auto_analyze(self, table_id: int) -> bool:
        ts = self._cache.get(table_id)
        if ts is None or ts.pseudo:
            return self._deltas.get(table_id, 0) > 0
        base = max(1, ts.count)
        return self._deltas.get(table_id, 0) / base >= \
            self.AUTO_ANALYZE_RATIO

    def pending_tables(self) -> list[int]:
        """Table ids with uncollected DML deltas (auto-analyze candidates,
        ref: statistics/update.go:135 + handle.go auto-analyze tick).
        Loads persisted stats first so a fresh process doesn't full-
        analyze a huge table over a one-row delta."""
        out = []
        for tid in list(self._deltas):
            self.get(tid)   # populate _cache from storage if persisted
            if self.need_auto_analyze(tid):
                out.append(tid)
        return out

    # -- query feedback (ref: statistics/update.go:88 QueryFeedback) ---------

    FEEDBACK_MIN_FACTOR = 0.2
    FEEDBACK_MAX_FACTOR = 5.0
    FEEDBACK_DEADBAND = 0.25   # |factor-1| below this: estimate is fine

    def feedback_range(self, table_id: int, col_id: int, dranges,
                       actual: int) -> None:
        """A pure range scan observed `actual` rows where the histogram
        estimated otherwise: rescale the overlapped buckets so future
        estimates track reality. In-memory only (like the reference's
        feedback before its periodic dump); version-bumped so cached
        plans re-cost."""
        ts = self._cache.get(table_id)
        if ts is None or ts.pseudo:
            return
        cs = ts.columns.get(col_id)
        if cs is None or not cs.hist.uppers:
            return
        with self._fb_mu:   # one feedback writer at a time
            est = ts.col_ranges_row_count(col_id, dranges)
            factor = (actual + 1.0) / (est + 1.0)
            factor = min(self.FEEDBACK_MAX_FACTOR,
                         max(self.FEEDBACK_MIN_FACTOR, factor))
            if abs(factor - 1.0) < self.FEEDBACK_DEADBAND:
                return
            hist = cs.hist
            touched = set()
            for r in dranges:
                lo = r.low[0] if r.low and not r.low_unbounded else None
                hi = r.high[0] if r.high and not r.high_unbounded else None
                if isinstance(lo, tuple):
                    lo = lo[1]
                if isinstance(hi, tuple):
                    hi = hi[1]
                i0 = 0 if lo is None else hist._locate(lo)
                i1 = hist.num_buckets - 1 if hi is None \
                    else hist._locate(hi)
                for i in range(max(0, i0),
                               min(hist.num_buckets - 1, i1) + 1):
                    touched.add(i)
            if not touched:
                return
            incr = [hist._bucket_count(i) for i in range(hist.num_buckets)]
            new_repeats = list(hist.repeats)
            for i in touched:
                incr[i] = int(round(incr[i] * factor))
                new_repeats[i] = int(round(new_repeats[i] * factor))
            new_counts = []
            run = 0
            for v in incr:
                run += v
                new_counts.append(run)
            # build-then-swap: concurrent READERS always see internally
            # consistent (monotonic) arrays
            hist.repeats = new_repeats
            hist.counts = new_counts
            hist.total = run
            self.version += 1
