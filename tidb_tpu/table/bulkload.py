"""Vectorized offline bulk load: columnar arrays -> committed KV pairs.

Reference: /root/reference/util/kvencoder (standalone KV-pair encoder for
offline import) and the SQL LOAD path's row encoding (tablecodec.go
EncodeRow). The per-row Python encoder (tablecodec.encode_row) manages
~100k rows/s; loading a TPC-H scale factor through it would dominate any
benchmark run. Here the whole memcomparable row encoding is computed as
numpy byte-matrix math — flag bytes, sign-flipped big-endian ints, IEEE754
float tricks, group-stuffed strings — then sliced into per-row bytes and
ingested through MVCCStore.bulk_import at one commit timestamp.

The byte format is exactly tidb_tpu.codec's (tested round-trip against the
scalar encoder); any divergence would corrupt the store, so tests compare
against tablecodec.encode_row on every column kind.
"""

from __future__ import annotations

import numpy as np

from tidb_tpu import codec, kv, tablecodec
from tidb_tpu.sqltypes import EvalType

__all__ = ["bulk_load", "encode_record_keys", "encode_rows_columnar"]

_SIGN = np.uint64(1 << 63)


def _be_bytes(u64: np.ndarray) -> np.ndarray:
    """uint64 array -> (n, 8) big-endian byte matrix."""
    return u64.astype(">u8").view(np.uint8).reshape(-1, 8)


def _int_payload(data: np.ndarray) -> np.ndarray:
    return _be_bytes(data.astype(np.int64).view(np.uint64) ^ _SIGN)


def _float_payload(data: np.ndarray) -> np.ndarray:
    d = data.astype(np.float64)
    u = d.view(np.uint64)
    # value test (not sign-bit) so -0.0 encodes as +0.0 (codec.encode_float)
    u = np.where(d >= 0, u | _SIGN, ~u)
    return _be_bytes(u)


def encode_record_keys(table_id: int, handles: np.ndarray) -> list[bytes]:
    """Vectorized tablecodec.record_key for every handle."""
    prefix = np.frombuffer(tablecodec.record_prefix(table_id), np.uint8)
    n = len(handles)
    mat = np.empty((n, len(prefix) + 8), dtype=np.uint8)
    mat[:, :len(prefix)] = prefix
    mat[:, len(prefix):] = _int_payload(np.asarray(handles))
    blob = mat.tobytes()
    w = mat.shape[1]
    return [blob[i * w:(i + 1) * w] for i in range(n)]


def _string_encodings(values) -> list[bytes]:
    """codec-encoded bytes (flag included) per distinct value."""
    out = []
    for v in values:
        s = v.encode("utf8") if isinstance(v, str) else bytes(v)
        out.append(bytes([codec.BYTES_FLAG]) + codec.encode_bytes(s))
    return out


class _ColPlan:
    """Per-column encode plan: widths per row + a scatter function."""

    def __init__(self, col, data, valid):
        self.col = col
        self.valid = valid
        n = len(valid)
        et = col.ft.eval_type
        self.str_encs = None
        self.codes = None
        if et == EvalType.STRING:
            # dictionary pass: distinct values encoded once, rows scatter
            # by code (BYTES encoding width varies with value length)
            arr = np.asarray(data, dtype=object)
            safe = np.where(valid, arr, "")
            uniq, codes = np.unique(safe.astype(str), return_inverse=True)
            self.str_encs = _string_encodings(uniq)
            self.codes = codes
            enc_lens = np.array([len(e) for e in self.str_encs],
                                dtype=np.int64)
            self.widths = np.where(valid, enc_lens[codes], 1)
        elif et == EvalType.DECIMAL:
            self.data = np.asarray(data, dtype=np.int64)  # scaled ints
            self.widths = np.where(valid, 10, 1)
        elif et == EvalType.REAL:
            self.data = np.asarray(data, dtype=np.float64)
            self.widths = np.where(valid, 9, 1)
        else:  # INT / DATETIME (epoch micros) / anything int64-shaped
            self.data = np.asarray(data, dtype=np.int64)
            self.widths = np.where(valid, 9, 1)
        assert len(self.widths) == n

    def scatter(self, out: np.ndarray, starts: np.ndarray) -> None:
        """Write this column's datums at byte offsets `starts`."""
        valid = self.valid
        nulls = np.flatnonzero(~valid)
        out[starts[nulls]] = codec.NIL_FLAG
        live = np.flatnonzero(valid)
        if not len(live):
            return
        pos = starts[live]
        et = self.col.ft.eval_type
        if et == EvalType.STRING:
            codes_live = self.codes[live]
            for code, enc in enumerate(self.str_encs):
                rows = pos[codes_live == code]
                if not len(rows):
                    continue
                mat = np.frombuffer(enc, np.uint8)
                out[rows[:, None] + np.arange(len(enc))] = mat
            return
        if et == EvalType.DECIMAL:
            out[pos] = codec.DECIMAL_FLAG
            out[pos + 1] = self.col.ft.frac
            out[(pos + 2)[:, None] + np.arange(8)] = \
                _int_payload(self.data[live])
            return
        if et == EvalType.REAL:
            out[pos] = codec.FLOAT_FLAG
            out[(pos + 1)[:, None] + np.arange(8)] = \
                _float_payload(self.data[live])
            return
        out[pos] = codec.INT_FLAG
        out[(pos + 1)[:, None] + np.arange(8)] = \
            _int_payload(self.data[live])


def encode_rows_columnar(cols, plans) -> list[bytes]:
    """-> per-row encoded value bytes. cols: ColumnInfo list (id order);
    plans: matching _ColPlan list."""
    n = len(plans[0].valid) if plans else 0
    cid_w = 9  # encode_datum(col_id): INT flag + 8 bytes
    # per-row total width and per-column start offsets
    row_w = np.zeros(n, dtype=np.int64)
    col_starts = []
    for p in plans:
        col_starts.append(row_w + cid_w)         # after this col's id datum
        row_w = row_w + cid_w + p.widths
    row_starts = np.concatenate(([0], np.cumsum(row_w)))
    total = int(row_starts[-1])
    out = np.zeros(total, dtype=np.uint8)
    for col, p, rel in zip(cols, plans, col_starts):
        id_pos = row_starts[:-1] + (rel - cid_w)
        out[id_pos] = codec.INT_FLAG
        out[(id_pos + 1)[:, None] + np.arange(8)] = np.broadcast_to(
            _int_payload(np.array([col.id]))[0], (n, 8))
        p.scatter(out, row_starts[:-1] + rel)
    blob = out.tobytes()
    return [blob[row_starts[i]:row_starts[i + 1]] for i in range(n)]


def bulk_load(storage, table, columns: dict, handles=None,
              rebase_autoid: bool = True) -> int:
    """Ingest columnar data into a table as one committed import.

    table: a tidb_tpu.table.Table. columns: {lower col name: array | (data,
    valid)} for every public column — int64 for INT/DATE/DATETIME (epoch
    micros), float64 for REAL, column-frac scaled int64 for DECIMAL, object
    str for STRING. handles: int64 row handles (defaults to the
    pk-is-handle column). Tables with secondary indexes are refused (the
    offline importer writes record keys only). -> rows ingested."""
    info = table.info
    if info.writable_indexes():
        raise kv.KVError("bulk_load: secondary indexes unsupported")
    pub = info.public_columns()
    missing = [c.name for c in pub if c.name.lower() not in columns]
    if missing:
        raise kv.KVError(f"bulk_load: missing columns {missing}")
    plans = []
    n = None
    for c in pub:
        v = columns[c.name.lower()]
        data, valid = v if isinstance(v, tuple) else (
            v, np.ones(len(v), dtype=bool))
        if n is None:
            n = len(valid)
        elif len(valid) != n:
            raise kv.KVError("bulk_load: column length mismatch")
        plans.append(_ColPlan(c, data, valid))
    if n is None or n == 0:
        return 0
    if handles is None:
        if not info.pk_is_handle:
            raise kv.KVError("bulk_load: handles required without int pk")
        names = [c.name.lower() for c in pub]
        pk_plan = plans[names.index(info.pk_col_name.lower())]
        if not pk_plan.valid.all():
            raise kv.KVError("bulk_load: NULL primary key")
        handles = pk_plan.data
    handles = np.asarray(handles, dtype=np.int64)
    # sorted-by-key ingest keeps the engine's ordered index append-friendly
    order = np.argsort(handles, kind="stable")
    plans = [_reorder(p, order) for p in plans]
    handles = handles[order]
    if np.any(np.diff(handles) == 0):
        raise kv.KVError("bulk_load: duplicate handles")
    keys = encode_record_keys(info.id, handles)
    values = encode_rows_columnar(pub, plans)
    start_ts = storage.current_ts()
    commit_ts = storage.current_ts()
    storage.engine.bulk_import(zip(keys, values), start_ts, commit_ts)
    if rebase_autoid and len(handles):
        table.rebase_auto_id(int(handles.max()))
    return n


def _reorder(p: _ColPlan, order: np.ndarray) -> _ColPlan:
    p.valid = p.valid[order]
    p.widths = p.widths[order]
    if p.codes is not None:
        p.codes = p.codes[order]
    else:
        p.data = p.data[order]
    return p
