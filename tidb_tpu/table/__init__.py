"""Row-level table operations over the transactional KV store.

Reference: /root/reference/table/tables/tables.go — AddRecord (:309),
RowWithCols (:442), index maintenance (:601, table/tables/index.go);
key layout via tablecodec.

Datum conventions at this layer (matching sqltypes):
    INT/DATETIME/DURATION -> python int (epoch micros for times)
    REAL                  -> float
    DECIMAL               -> (frac, scaled_int) tuple in KV, scaled per
                             column frac in chunks
    STRING                -> str/bytes
"""

from __future__ import annotations

import numpy as np

import threading
import weakref

from tidb_tpu import codec, kv, tablecodec
from tidb_tpu.chunk import Chunk, Column
from tidb_tpu.schema.model import IndexInfo, SchemaState, TableInfo
from tidb_tpu.sqltypes import (EvalType, FieldType, TypeCode,
                               decimal_to_scaled, np_dtype_for,
                               scaled_to_decimal)

__all__ = ["Table", "DupKeyError", "encode_datum_for_col",
           "decode_datum_for_col", "rows_to_chunk", "kvrows_to_chunk"]


class DupKeyError(kv.KVError):
    def __init__(self, key_desc: str):
        super().__init__(f"Duplicate entry for key '{key_desc}'")


def _normalize_enum_set(v, ft: FieldType):
    """ENUM: member string (or 1-based ordinal) -> the member, validated.
    SET: comma list (or bitmask) -> members deduped in definition order.
    Values are STORED as their member strings (a documented departure
    from MySQL's ordinal storage: comparisons/sorts here are by string,
    not by member index). Ref: types/enum.go, types/set.go."""
    elems = ft.elems
    if ft.tp == TypeCode.ENUM:
        if isinstance(v, (int,)) and not isinstance(v, bool):
            if not (1 <= v <= len(elems)):
                raise kv.KVError(f"invalid enum ordinal {v}")
            return elems[v - 1]
        sv = v if isinstance(v, str) else str(v)
        for e in elems:
            if e.lower() == sv.lower():
                return e
        raise kv.KVError(f"invalid enum value {sv!r} "
                         f"(members: {', '.join(elems)})")
    # SET
    if isinstance(v, int) and not isinstance(v, bool):
        if not (0 <= v < 1 << len(elems)):
            raise kv.KVError(f"invalid set bitmask {v}")
        return ",".join(e for i, e in enumerate(elems) if v >> i & 1)
    sv = v if isinstance(v, str) else str(v)
    if sv == "":
        return ""
    chosen = []
    for part in sv.split(","):
        hit = next((e for e in elems
                    if e.lower() == part.strip().lower()), None)
        if hit is None:
            raise kv.KVError(f"invalid set member {part!r} "
                             f"(members: {', '.join(elems)})")
        if hit not in chosen:
            chosen.append(hit)
    return ",".join(e for e in elems if e in chosen)


def encode_datum_for_col(v, ft: FieldType):
    """Python value -> KV datum representation."""
    if v is None:
        return None
    if ft.eval_type == EvalType.DECIMAL:
        # normalize to the column's scale: the memcomparable decimal
        # encoding orders by (frac, scaled), so every stored datum of a
        # column MUST share the column frac or index ranges break
        wide = ft.is_wide_decimal
        if isinstance(v, tuple):
            frac, scaled = v
            out = (ft.frac, _rescale_decimal(scaled, frac, ft.frac))
        else:
            out = (ft.frac, decimal_to_scaled(v, ft.frac, wide=wide))
        if ft.flen > 0 and abs(out[1]) >= 10 ** (
                ft.flen if wide else min(ft.flen, 18)):
            # MySQL strict mode: out-of-range decimal is an error, never
            # a silently stored wider value
            raise kv.KVError(
                f"Out of range value for DECIMAL({ft.flen},{ft.frac})")
        return out
    if ft.tp in (TypeCode.ENUM, TypeCode.SET):
        return _normalize_enum_set(v, ft)
    if ft.tp == TypeCode.JSON:
        # canonical compact text (ref: types/json/binary.go stores a
        # binary form; text keeps the column host-side and printable)
        import json as _json
        if isinstance(v, tuple):       # decimal datum -> a JSON number
            frac, scaled = v
            v = float(scaled_to_decimal(scaled, frac))
        if isinstance(v, (bytes, str)):
            try:
                return _json.dumps(_json.loads(v), separators=(",", ":"))
            except ValueError:
                raise kv.KVError(
                    f"Invalid JSON text: {str(v)[:64]!r}") from None
        return _json.dumps(v, separators=(",", ":"))
    if ft.eval_type == EvalType.STRING:
        return v if isinstance(v, (str, bytes)) else str(v)
    if isinstance(v, tuple):      # decimal datum into a non-decimal column
        frac, scaled = v
        if ft.eval_type == EvalType.REAL:
            return float(scaled_to_decimal(scaled, frac))
        # exact int64-safe rounding, MySQL half-away-from-zero
        q, r = divmod(abs(scaled), 10 ** frac)
        out = q + (1 if 2 * r >= 10 ** frac else 0)
        return out if scaled >= 0 else -out
    if ft.eval_type == EvalType.REAL:
        return float(v)
    if ft.eval_type == EvalType.DATETIME:
        if isinstance(v, str):
            from tidb_tpu.sqltypes import parse_datetime
            v = parse_datetime(v)
        # round micros to the column's fsp at the write, like MySQL
        # DATETIME(fsp) (frac 0 stores whole seconds — 00:00:00.5
        # becomes 00:00:01, never a displayed fraction later)
        step = 10 ** (6 - min(max(ft.frac, 0), 6))
        if step > 1:
            v = ((int(v) + step // 2) // step) * step
        return int(v)
    if isinstance(v, float):      # MySQL rounds halves away from zero
        import math
        return int(math.floor(v + 0.5)) if v >= 0 else int(math.ceil(v - 0.5))
    return int(v)


def _rescale_decimal(scaled: int, frac: int, to_frac: int) -> int:
    """Change a scaled decimal's scale; MySQL half-away-from-zero when
    dropping digits."""
    if to_frac == frac:
        return scaled
    if to_frac > frac:
        return scaled * (10 ** (to_frac - frac))
    div = 10 ** (frac - to_frac)
    q, r = divmod(abs(scaled), div)
    out = q + (1 if 2 * r >= div else 0)
    return out if scaled >= 0 else -out


def decode_datum_for_col(v, ft: FieldType):
    """KV datum -> chunk-layer value (scaled int for decimals)."""
    if v is None:
        return None
    if ft.eval_type == EvalType.DECIMAL:
        frac, scaled = v
        return _rescale_decimal(scaled, frac, ft.frac)
    if ft.eval_type in (EvalType.STRING, EvalType.JSON) and \
            isinstance(v, bytes):
        # JSON text decodes here too: filters/joins on JSON columns must
        # see str, not bytes (presentation is too late)
        try:
            return v.decode("utf8")
        except UnicodeDecodeError:
            return v
    return v


# auto-increment batch caches shared across per-statement Table objects:
# storage -> {table_id: [next, last]} (ref: autoid.go:36 Allocator held
# by the domain, not the statement)
_AUTO_REGISTRY: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_AUTO_LOCK = threading.Lock()


class Table:
    """Operations for one table inside caller-provided transactions."""

    def __init__(self, info: TableInfo, storage):
        self.info = info
        self.storage = storage  # for auto-id allocation meta txns

    # -- auto increment ------------------------------------------------------

    AUTO_ID_STEP = 4000  # ref: meta/autoid allocator batch (autoid.go:36)

    # first id this Table instance generated: the LAST_INSERT_ID source
    # (MySQL reports the FIRST value generated by the last INSERT)
    first_alloc_id: int | None = None

    def _auto_cache_slot(self) -> list:
        """Shared [next, last] batch per (storage, table id). Table
        objects are per-statement, but the allocator must persist across
        statements like the reference's domain-held autoid.Allocator
        (autoid.go:36) — else every INSERT burns a fresh 4000-id batch
        and ids jump 1, 4001, 8001..."""
        caches = _AUTO_REGISTRY.get(self.storage)
        if caches is None:
            caches = _AUTO_REGISTRY.setdefault(self.storage, {})
        slot = caches.get(self.info.id)
        if slot is None:
            slot = caches[self.info.id] = [1, 0]   # empty range
        return slot

    def alloc_auto_id(self, track: bool = True) -> int:
        out = None
        with _AUTO_LOCK:
            slot = self._auto_cache_slot()
            if slot[0] <= slot[1]:
                out = slot[0]
                slot[0] += 1
        if out is None:
            # batch refill OUTSIDE the lock: the meta txn must not
            # serialize inserts on unrelated tables. Two racing refills
            # allocate distinct ranges (meta inc is transactional); the
            # loser's leftover range is skipped, ids just gap.
            from tidb_tpu.meta import Meta
            txn = self.storage.begin()
            try:
                first, last = Meta(txn).gen_auto_id(
                    self.info.id, self.AUTO_ID_STEP)
                txn.commit()
            except Exception:
                txn.rollback()
                raise
            out = first
            with _AUTO_LOCK:
                slot = self._auto_cache_slot()
                if last > slot[1]:
                    slot[0], slot[1] = first + 1, last
        # only user-visible AUTO_INCREMENT allocations feed
        # LAST_INSERT_ID; the hidden _tidb_rowid handle does not (MySQL
        # returns 0 after inserting into a table with no auto column)
        if track and self.first_alloc_id is None:
            self.first_alloc_id = out
        return out

    def rebase_auto_id(self, at_least: int) -> None:
        from tidb_tpu.meta import Meta
        txn = self.storage.begin()
        try:
            Meta(txn).rebase_auto_id(self.info.id, at_least)
            txn.commit()
        except Exception:
            txn.rollback()
            raise
        with _AUTO_LOCK:
            slot = self._auto_cache_slot()
            if slot[0] <= at_least <= slot[1]:
                # explicit id landed inside the cached batch: skip past
                # it (ref: autoid.go Rebase with newBase <= alloc.end)
                slot[0] = at_least + 1
            elif at_least > slot[1]:
                slot[0], slot[1] = 1, 0   # force a fresh meta batch

    # -- write path ----------------------------------------------------------

    def add_record(self, txn: kv.Transaction, values: dict[str, object],
                   handle: int | None = None, skip_dup_check: bool = False
                   ) -> int:
        """Insert one row; values keyed by lower column name. Returns the
        handle. Ref: tables.go:309 AddRecord."""
        info = self.info
        row_vals = {}
        for col in info.writable_columns():
            cname = col.name.lower()
            if cname in values:
                v = values[cname]
                # explicit NULL: auto-inc still allocates (MySQL), NOT NULL
                # errors; it is NOT replaced by the default
                if v is None and col.auto_increment:
                    v = self.alloc_auto_id()
                elif v is None and col.ft.not_null and \
                        col.state == SchemaState.PUBLIC:
                    raise kv.KVError(f"column '{col.name}' cannot be null")
            else:
                # omitted column: default / auto-increment
                if col.auto_increment:
                    v = self.alloc_auto_id()
                elif col.has_default:
                    v = col.default
                    if v == "CURRENT_TIMESTAMP" and \
                            col.ft.eval_type == EvalType.DATETIME:
                        v = _now_micros()   # evaluated per insert
                elif col.ft.not_null and col.state == SchemaState.PUBLIC:
                    raise kv.KVError(f"column '{col.name}' cannot be null")
                else:
                    v = None
            row_vals[col.id] = encode_datum_for_col(v, col.ft) \
                if v is not None else None

        if handle is None:
            if info.pk_is_handle:
                pk = info.col_by_name(info.pk_col_name)
                hv = row_vals.get(pk.id)
                if hv is None:
                    raise kv.KVError("primary key cannot be null")
                handle = int(hv)
                self.rebase_auto_id(handle) if pk.auto_increment else None
            else:
                handle = self.alloc_auto_id(track=False)

        rk = tablecodec.record_key(info.id, handle)
        if not skip_dup_check:
            if info.pk_is_handle and txn.get(rk) is not None:
                raise DupKeyError(f"{handle} for key 'PRIMARY'")
        # indexes first (unique checks), then the row
        for idx in self.info.writable_indexes():
            self._add_index_entry(txn, idx, row_vals, handle,
                                  check_dup=not skip_dup_check)
        col_ids = sorted(row_vals)
        txn.set(rk, tablecodec.encode_row(
            col_ids, [row_vals[c] for c in col_ids]))
        return handle

    def _index_values(self, idx: IndexInfo, row_vals: dict[int, object]):
        """Index-key datums for one row. _ci string columns contribute
        their casefolded collation key, so memcomparable byte order IS
        collation order and unique indexes reject case-duplicates (ref:
        collation-aware index encoding; the row itself keeps the
        original value — indexes on _ci columns are never covering)."""
        out = []
        for cname in idx.columns:
            col = self.info.col_by_name(cname)
            v = row_vals.get(col.id)
            if col.ft.is_ci and isinstance(v, str):
                from tidb_tpu.sqltypes import collation_key
                v = collation_key(v)
            out.append(v)
        return out

    def _add_index_entry(self, txn, idx: IndexInfo,
                         row_vals: dict[int, object], handle: int,
                         check_dup: bool) -> None:
        vals = self._index_values(idx, row_vals)
        if idx.unique and all(v is not None for v in vals):
            ik = tablecodec.index_key(self.info.id, idx.id, vals)
            if check_dup:
                existing = txn.get(ik)
                if existing is not None:
                    raise DupKeyError(f"{vals} for key '{idx.name}'")
            txn.set(ik, codec.encode_int(handle))
        else:
            # non-unique (or unique w/ NULL part): handle in the key
            ik = tablecodec.index_key(self.info.id, idx.id, vals,
                                      handle=handle)
            txn.set(ik, b"0")

    def remove_record(self, txn: kv.Transaction, handle: int,
                      row_vals: dict[int, object]) -> None:
        """Ref: tables.go RemoveRecord + DeletableIndices."""
        txn.delete(tablecodec.record_key(self.info.id, handle))
        for idx in self.info.deletable_indexes():
            vals = self._index_values(idx, row_vals)
            if idx.unique and all(v is not None for v in vals):
                txn.delete(tablecodec.index_key(self.info.id, idx.id, vals))
            else:
                txn.delete(tablecodec.index_key(self.info.id, idx.id, vals,
                                                handle=handle))

    def update_record(self, txn: kv.Transaction, handle: int,
                      old_vals: dict[int, object],
                      new_values: dict[str, object]) -> None:
        """new_values keyed by lower column name (python values)."""
        merged = dict(old_vals)
        for name, v in new_values.items():
            col = self.info.col_by_name(name)
            merged[col.id] = encode_datum_for_col(v, col.ft) \
                if v is not None else None
        self.remove_record(txn, handle, old_vals)
        col_ids = sorted(merged)
        rk = tablecodec.record_key(self.info.id, handle)
        for idx in self.info.writable_indexes():
            self._add_index_entry(txn, idx, merged, handle, check_dup=True)
        txn.set(rk, tablecodec.encode_row(
            col_ids, [merged[c] for c in col_ids]))

    # -- read path -----------------------------------------------------------

    def row_by_handle(self, retriever, handle: int) -> dict[int, object] | None:
        raw = retriever.get(tablecodec.record_key(self.info.id, handle))
        if raw is None:
            return None
        return tablecodec.decode_row(raw)

    def iter_records(self, retriever, start_handle: int | None = None):
        """Yields (handle, {col_id: datum}). Ref: tables.go IterRecords."""
        info = self.info
        start = tablecodec.record_key(info.id, start_handle) \
            if start_handle is not None else tablecodec.record_prefix(info.id)
        end = codec.prefix_next(tablecodec.record_prefix(info.id))
        for k, v in retriever.iter_range(start, end):
            _tid, handle = tablecodec.decode_record_key(k)
            yield handle, tablecodec.decode_row(v)


def index_kvrows_to_chunk(info: TableInfo, idx: IndexInfo, col_infos,
                          kvrows, handle_col: int | None = None) -> Chunk:
    """Decode raw index (key, value) pairs into a chunk of the requested
    index columns (+ handle). Non-unique entries carry the handle as the
    key's last datum; unique entries carry it in the value
    (ref: tablecodec.go index layout, table/tables/index.go)."""
    from tidb_tpu import codec as _codec
    from tidb_tpu.sqltypes import new_int_field
    n_idx_cols = len(idx.columns)
    # map requested col name -> position among the index's columns
    pos_by_name = {c.lower(): i for i, c in enumerate(idx.columns)}
    ncols = len(col_infos) + (1 if handle_col is not None else 0)
    rows = []
    for k, v in kvrows:
        _tid, _iid, suffix = tablecodec.decode_index_key(k)
        vals = _codec.decode_key(suffix)
        if len(vals) > n_idx_cols:          # handle stored in-key
            handle = vals[n_idx_cols]
            vals = vals[:n_idx_cols]
        else:                               # unique entry: handle in value
            handle, _ = _codec.decode_int(v, 0)
        row = []
        src = 0
        for j in range(ncols):
            if handle_col is not None and j == handle_col:
                row.append(handle)
                continue
            ci = col_infos[src]
            src += 1
            pos = pos_by_name.get(ci.name.lower())
            # pk-is-handle column is not among index columns; its value IS
            # the handle (covering-index reads rely on this)
            row.append(handle if pos is None else vals[pos])
        rows.append(row)
    fts = []
    src = 0
    for j in range(ncols):
        if handle_col is not None and j == handle_col:
            fts.append(new_int_field())
        else:
            fts.append(col_infos[src].ft)
            src += 1
    return rows_to_chunk(fts, rows)


def rows_to_chunk(fts: list[FieldType], rows: list[list]) -> Chunk:
    """Build a chunk from decoded python values (decimals may be tuples)."""
    cols = []
    for j, ft in enumerate(fts):
        vals = [decode_datum_for_col(r[j], ft) for r in rows]
        dtype = np_dtype_for(ft.tp, ft.flen)
        valid = np.array([v is not None for v in vals], dtype=bool)
        if dtype == np.dtype(object):
            from tidb_tpu.sqltypes import object_fill
            fill = object_fill(ft)
            data = np.empty(len(vals), dtype=object)
            for i, v in enumerate(vals):
                data[i] = v if v is not None else fill
        else:
            data = np.zeros(len(vals), dtype=dtype)
            for i, v in enumerate(vals):
                if v is not None:
                    data[i] = v
        cols.append(Column(ft, data, valid))
    return Chunk(cols)


def _kvrows_to_chunk_native(col_infos, kvrows,
                            with_handle_col: int | None) -> Chunk | None:
    """C++ batch decode straight into columnar buffers (native/codec.cc).
    Handles fixed-width columns only; None -> caller uses the Python
    loop (varlen columns, unusual encodings, no compiler)."""
    from tidb_tpu.native import (NATIVE_KIND_DECIMAL, NATIVE_KIND_FLOAT,
                                 NATIVE_KIND_HANDLE, NATIVE_KIND_INT,
                                 decode_rows_native)
    from tidb_tpu.sqltypes import new_int_field
    ncols = len(col_infos) + (1 if with_handle_col is not None else 0)
    specs = []
    fts = []
    src = 0
    for j in range(ncols):
        if with_handle_col is not None and j == with_handle_col:
            specs.append((0, NATIVE_KIND_HANDLE, 0, False, None))
            fts.append(new_int_field())
            continue
        ci = col_infos[src]
        src += 1
        et = ci.ft.eval_type
        if et in (EvalType.INT, EvalType.DATETIME):
            kind = NATIVE_KIND_INT
        elif et == EvalType.REAL:
            kind = NATIVE_KIND_FLOAT
        elif et == EvalType.DECIMAL:
            kind = NATIVE_KIND_DECIMAL
        else:
            return None   # varlen: python path
        default = None
        if ci.has_default and ci.default is not None:
            default = encode_datum_for_col(ci.default, ci.ft)
            if isinstance(default, tuple):
                default = default[1]   # scaled int at the column's frac
        specs.append((ci.id, kind, ci.ft.frac, ci.has_default, default))
        fts.append(ci.ft)
    out = decode_rows_native(kvrows, specs)
    if out is None:
        return None
    datas, valids = out
    return Chunk([Column(ft, d, v)
                  for ft, d, v in zip(fts, datas, valids)])


def kvrows_to_chunk(info: TableInfo, col_infos, kvrows,
                    with_handle_col: int | None = None) -> Chunk:
    """Decode raw (key, value) record pairs into a chunk of the requested
    columns. col_infos: list of ColumnInfo to emit, in order.
    with_handle_col: emit the row handle as an extra int column at this
    output position (DML readers need it to address rows).
    Fast path: the C++ batch decoder (ref: util/codec DecodeOneToChunk,
    codec.go:387 — and the Rust TiKV decode the reference leans on)."""
    from tidb_tpu.sqltypes import new_int_field
    # wide-decimal datums use variable-length encodings the C++ walker
    # doesn't know; any such column in the ROW (even unrequested) gates
    # the whole table to the python decode path
    ch = None
    if not any(c.ft.is_wide_decimal for c in info.columns):
        ch = _kvrows_to_chunk_native(col_infos, kvrows, with_handle_col)
    if ch is not None:
        return ch
    ncols = len(col_infos) + (1 if with_handle_col is not None else 0)
    rows = []
    for k, v in kvrows:
        _tid, handle = tablecodec.decode_record_key(k)
        d = tablecodec.decode_row(v)
        row = []
        src = 0
        for j in range(ncols):
            if with_handle_col is not None and j == with_handle_col:
                row.append(handle)
                continue
            ci = col_infos[src]
            src += 1
            if ci.id in d:
                val = d[ci.id]   # stored value, including explicit NULL
            elif ci.has_default:
                # row written before ALTER ADD COLUMN: synthesize default
                val = encode_datum_for_col(ci.default, ci.ft)
            else:
                val = None
            row.append(val)
        rows.append(row)
    fts = []
    src = 0
    for j in range(ncols):
        if with_handle_col is not None and j == with_handle_col:
            fts.append(new_int_field())
        else:
            fts.append(col_infos[src].ft)
            src += 1
    return rows_to_chunk(fts, rows)
