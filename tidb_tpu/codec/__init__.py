"""Memcomparable datum codec: byte strings whose lexicographic order equals
datum order.

Reference: /root/reference/util/codec/ — EncodeKey codec/codec.go:165, the
MyRocks-style byte-group stuffing codec/bytes.go:45, int sign-bit flip
codec/number.go. The wire format here follows the same public scheme
(8-byte groups + pad-count marker; sign-flipped big-endian ints; IEEE754
bit tricks for floats) so ordering properties match, but is written fresh.

Flags (1 byte before each datum):
    0x00 NULL        sorts before everything
    0x01 BYTES       group-stuffed, order-preserving
    0x03 INT         big-endian uint64 of (v XOR 1<<63)
    0x04 UINT        big-endian uint64
    0x05 FLOAT       IEEE754 with sign-dependent bit flip
    0x06 DECIMAL     frac byte + INT encoding of scaled value (per-column
                     frac is constant, so order holds within a column)
    0x02 WDEC_NEG    wide decimal, scaled < -2^63: frac byte + inverted
                     length byte + complemented big-endian magnitude
    0x07 WDEC_POS    wide decimal, scaled >= 2^63: frac byte + length
                     byte + big-endian magnitude
                     (0x02 < 0x06 < 0x07, so a column mixing narrow and
                     wide scaled values still orders correctly — ref:
                     types/mydecimal.go's sortable binary form)
    0xFF MAX         sorts after everything (range upper bounds)

Descending order: `encode_desc` inverts every payload byte.
"""

from __future__ import annotations

import struct

__all__ = [
    "NIL_FLAG", "BYTES_FLAG", "INT_FLAG", "UINT_FLAG", "FLOAT_FLAG",
    "DECIMAL_FLAG", "MAX_FLAG",
    "encode_int", "decode_int", "encode_uint", "decode_uint",
    "encode_bytes", "decode_bytes", "encode_float", "decode_float",
    "encode_datum", "encode_key", "decode_key", "decode_one",
    "key_max", "key_next",
]

NIL_FLAG = 0x00
BYTES_FLAG = 0x01
WDEC_NEG_FLAG = 0x02
INT_FLAG = 0x03
UINT_FLAG = 0x04
FLOAT_FLAG = 0x05
DECIMAL_FLAG = 0x06
WDEC_POS_FLAG = 0x07
NIL_DESC_FLAG = 0xFE  # NULL under DESC order: sorts after every value
MAX_FLAG = 0xFF

_SIGN_MASK = 0x8000000000000000
_GROUP = 8
_MARKER = 0xFF
_PAD = 0x00


# -- primitives --------------------------------------------------------------

def _unpack_u64(b: bytes, off: int) -> int:
    if off + 8 > len(b):
        raise ValueError("truncated 8-byte datum")
    (u,) = struct.unpack_from(">Q", b, off)
    return u


def encode_int(v: int) -> bytes:
    """Sign-flipped big-endian: order-preserving over int64."""
    if not (-(1 << 63) <= v < (1 << 63)):
        raise OverflowError(f"{v} outside int64")
    return struct.pack(">Q", (v ^ _SIGN_MASK) & 0xFFFFFFFFFFFFFFFF)


def decode_int(b: bytes, off: int = 0) -> tuple[int, int]:
    u = _unpack_u64(b, off) ^ _SIGN_MASK
    if u >= 1 << 63:
        u -= 1 << 64
    return u, off + 8


def encode_uint(v: int) -> bytes:
    if not (0 <= v < (1 << 64)):
        raise OverflowError(f"{v} outside uint64")
    return struct.pack(">Q", v)


def decode_uint(b: bytes, off: int = 0) -> tuple[int, int]:
    return _unpack_u64(b, off), off + 8


def encode_float(v: float) -> bytes:
    (u,) = struct.unpack(">Q", struct.pack(">d", v))
    # value test (not sign-bit test) so -0.0 encodes identically to +0.0,
    # matching the reference (util/codec/float.go uses `f >= 0`)
    if v >= 0:
        u |= _SIGN_MASK               # non-negative: set sign bit
    else:
        u = ~u & 0xFFFFFFFFFFFFFFFF   # negative: flip all bits
    return struct.pack(">Q", u)


def decode_float(b: bytes, off: int = 0) -> tuple[float, int]:
    u = _unpack_u64(b, off)
    if u & _SIGN_MASK:
        u &= ~_SIGN_MASK & 0xFFFFFFFFFFFFFFFF
    else:
        u = ~u & 0xFFFFFFFFFFFFFFFF
    (v,) = struct.unpack(">d", struct.pack(">Q", u))
    return v, off + 8


def encode_bytes(data: bytes) -> bytes:
    """Group-stuffing: emit 8-byte groups each followed by a marker byte.

    Marker = 0xFF - pad_count; a full group's marker is 0xFF (continue), the
    final (possibly empty) group's marker is < 0xFF (stop). Lexicographic
    order over encodings equals order over the original byte strings.
    """
    out = bytearray()
    i = 0
    n = len(data)
    while True:
        group = data[i:i + _GROUP]
        pad = _GROUP - len(group)
        out += group
        out += bytes([_PAD]) * pad
        out.append(_MARKER - pad)
        i += _GROUP
        if pad > 0:
            break
        if i == n:
            # data ended exactly on a boundary: emit terminating all-pad group
            out += bytes([_PAD]) * _GROUP
            out.append(_MARKER - _GROUP)
            break
    return bytes(out)


def decode_bytes(b: bytes, off: int = 0, desc: bool = False) -> tuple[bytes, int]:
    """Decode a group-stuffed byte string. With desc=True, inverts each
    9-byte group as it is consumed (no whole-tail copies)."""
    out = bytearray()
    while True:
        if off + _GROUP + 1 > len(b):
            raise ValueError("malformed bytes encoding")
        group = b[off:off + _GROUP]
        marker = b[off + _GROUP]
        if desc:
            group = bytes(0xFF - x for x in group)
            marker = 0xFF - marker
        off += _GROUP + 1
        pad = _MARKER - marker
        if pad == 0:
            out += group
            continue
        if pad > _GROUP:
            raise ValueError("malformed bytes marker")
        real = _GROUP - pad
        if any(x != _PAD for x in group[real:]):
            raise ValueError("nonzero padding")
        out += group[:real]
        return bytes(out), off


# -- datums ------------------------------------------------------------------

_I64_LO, _I64_HI = -(1 << 63), (1 << 63) - 1


def _encode_decimal(frac: int, scaled: int) -> bytes:
    """(frac, scaled) -> flagged bytes. Scaled values inside int64 use
    the fixed 8-byte DECIMAL form; wider ones use the variable-length
    WDEC forms whose flags straddle DECIMAL so mixed-width columns stay
    memcomparable (see the module docstring)."""
    if _I64_LO <= scaled <= _I64_HI:
        return bytes([DECIMAL_FLAG, frac]) + encode_int(scaled)
    if scaled > 0:
        mag = scaled.to_bytes((scaled.bit_length() + 7) // 8, "big")
        if len(mag) > 255:
            raise OverflowError("decimal magnitude too large")
        return bytes([WDEC_POS_FLAG, frac, len(mag)]) + mag
    m = -scaled
    mag = m.to_bytes((m.bit_length() + 7) // 8, "big")
    if len(mag) > 255:
        raise OverflowError("decimal magnitude too large")
    return bytes([WDEC_NEG_FLAG, frac, 255 - len(mag)]) + \
        bytes(0xFF - x for x in mag)


def encode_datum(v, desc: bool = False) -> bytes:
    """Encode one python-level value with a type flag.

    int -> INT; float -> FLOAT; str/bytes -> BYTES; None -> NULL;
    (frac, scaled) tuple -> DECIMAL. Datetimes arrive as int micros (INT).
    """
    if v is None:
        # DESC NULL gets its own high flag so it sorts after all values
        return bytes([NIL_DESC_FLAG if desc else NIL_FLAG])
    elif isinstance(v, bool):
        raw = bytes([INT_FLAG]) + encode_int(int(v))
    elif isinstance(v, int):
        if v >= 1 << 63:
            # unsigned BIGINT upper half: UINT flag sorts after all INTs,
            # keeping total order correct for unsigned columns
            raw = bytes([UINT_FLAG]) + encode_uint(v)
        else:
            raw = bytes([INT_FLAG]) + encode_int(v)
    elif isinstance(v, float):
        raw = bytes([FLOAT_FLAG]) + encode_float(v)
    elif isinstance(v, str):
        raw = bytes([BYTES_FLAG]) + encode_bytes(v.encode("utf8"))
    elif isinstance(v, (bytes, bytearray)):
        raw = bytes([BYTES_FLAG]) + encode_bytes(bytes(v))
    elif isinstance(v, tuple) and len(v) == 2:
        frac, scaled = v
        raw = _encode_decimal(frac, scaled)
    else:
        import decimal as _d
        if isinstance(v, _d.Decimal):
            from tidb_tpu.sqltypes import decimal_to_scaled
            frac = max(0, -v.as_tuple().exponent)
            raw = _encode_decimal(
                frac, decimal_to_scaled(v, frac, wide=True))
        else:
            raise TypeError(f"cannot encode datum {v!r} ({type(v)})")
    if desc:
        return bytes([raw[0]]) + bytes(0xFF - x for x in raw[1:])
    return raw


def decode_one(b: bytes, off: int = 0, desc: bool = False):
    """Decode one datum; returns (value, new_offset)."""
    flag = b[off]
    off += 1

    def inv8():
        if off + 8 > len(b):
            raise ValueError("truncated 8-byte datum")
        return bytes(0xFF - x for x in b[off:off + 8])

    if flag == NIL_FLAG or flag == NIL_DESC_FLAG:
        return None, off
    if flag == MAX_FLAG:
        raise ValueError("MAX flag is not decodable")
    if flag == INT_FLAG:
        if desc:
            return decode_int(inv8(), 0)[0], off + 8
        return decode_int(b, off)
    if flag == UINT_FLAG:
        if desc:
            return decode_uint(inv8(), 0)[0], off + 8
        return decode_uint(b, off)
    if flag == FLOAT_FLAG:
        if desc:
            return decode_float(inv8(), 0)[0], off + 8
        return decode_float(b, off)
    if flag == DECIMAL_FLAG:
        frac = b[off] if not desc else 0xFF - b[off]
        off += 1
        if desc:
            return (frac, decode_int(inv8(), 0)[0]), off + 8
        v, off = decode_int(b, off)
        return (frac, v), off
    if flag in (WDEC_POS_FLAG, WDEC_NEG_FLAG):
        def u8(x):
            return (0xFF - x) if desc else x
        frac = u8(b[off])
        ln = u8(b[off + 1])
        off += 2
        neg = flag == WDEC_NEG_FLAG
        if neg:
            ln = 255 - ln
        if off + ln > len(b):
            raise ValueError("truncated wide decimal")
        mag = bytes(u8(x) for x in b[off:off + ln])
        if neg:
            mag = bytes(0xFF - x for x in mag)
        v = int.from_bytes(mag, "big")
        return (frac, -v if neg else v), off + ln
    if flag == BYTES_FLAG:
        return decode_bytes(b, off, desc=desc)
    raise ValueError(f"unknown flag {flag:#x}")


def encode_key(values, desc_flags=None) -> bytes:
    """Encode a sequence of datums into one memcomparable key."""
    out = bytearray()
    for i, v in enumerate(values):
        desc = bool(desc_flags[i]) if desc_flags else False
        out += encode_datum(v, desc)
    return bytes(out)


def decode_key(b: bytes, desc_flags=None) -> list:
    out = []
    off = 0
    i = 0
    while off < len(b):
        desc = bool(desc_flags[i]) if desc_flags else False
        v, off = decode_one(b, off, desc)
        out.append(v)
        i += 1
    return out


def key_max() -> bytes:
    return bytes([MAX_FLAG])


def key_next(key: bytes) -> bytes:
    """Smallest key strictly greater than `key` (append 0x00)."""
    return key + b"\x00"


def prefix_next(prefix: bytes) -> bytes:
    """Smallest key strictly greater than every key starting with `prefix`
    (increment with carry). Raises for all-0xFF prefixes: no strict upper
    bound exists; callers must treat that range as unbounded."""
    b = bytearray(prefix)
    for i in range(len(b) - 1, -1, -1):
        if b[i] != 0xFF:
            b[i] += 1
            return bytes(b[:i + 1])
    raise ValueError("all-0xFF prefix has no strict upper bound")
