"""One device plane: the process-wide 1-D ``("batch",)`` mesh.

Single-chip and multi-chip execution share one layout language: rows are
``NamedSharding(mesh, PartitionSpec("batch"))`` (each chip holds a
contiguous row shard of the padded superchunk) and small/broadcast state
is ``PartitionSpec()`` (replicated). Every kernel — the fused copTask
agg, the mesh group-agg, the lookup join, the shuffle join — addresses
devices only through these two specs plus the ``"batch"`` axis name, so
the same compiled program drives 1 device and N devices; on one device
the collectives (psum-style merges, all_gather, all_to_all) are elided
at trace time by the ``ndev == 1`` guards and the program lowers to the
plain single-chip kernel. Under ``JAX_PLATFORMS=cpu`` a mesh of virtual
host devices behaves identically (the t5x pjit-on-cpu posture: jit IS
pjit, so no separate fallback wrapper is needed — ``plane_jit`` exists
as the one seam where that would change).

The mesh is a process property, like the reference's store topology
(store/tikv/coprocessor.go fan-out): one plane serves every session.
The planner consults ``active_mesh()`` to route plans, and bumps
``mesh_generation()`` into the plan-cache key so cached plans never
outlive a topology change; ``mesh_fingerprint()`` is the analogous
identity folded into kernel-cache and persistent compile-cache keys so
a 1-chip and an 8-chip executable for the same plan can never collide.

Concurrency: configuration happens at process start / test setup, on
one thread; readers (`active_mesh`, `mesh_generation`, `ndev`) see a
single attribute load each (atomic under the GIL), so no lock is
needed — the generation counter is the coherence protocol.
"""

from __future__ import annotations

import contextlib

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

try:        # jax >= 0.4.35 exposes shard_map at top level
    from jax import shard_map as _shard_map_fn
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map_fn

__all__ = [
    "AXIS", "build_mesh", "configure_mesh", "enable_mesh", "disable_mesh",
    "active_mesh", "mesh_generation", "on_topology_change", "ndev",
    "batch_spec", "replicated_spec", "batch_sharding", "replicated",
    "chip_device", "chip_scope", "mesh_fingerprint", "shard_map",
    "plane_jit",
]

#: the one data-parallel axis name of the device plane
AXIS = "batch"

_mesh: Mesh | None = None
_generation = 0
_listeners: list = []


# -- construction ----------------------------------------------------------

def build_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """A 1-D ``("batch",)`` mesh over the first n_devices jax devices."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), axis_names=(AXIS,))


# -- process configuration -------------------------------------------------

def on_topology_change(fn) -> None:
    """Register fn() to run after every mesh (re)configuration — kernel
    caches keyed on the generation use this to release compiled programs
    that can never be hit again (e.g. after disable_mesh)."""
    _listeners.append(fn)


def configure_mesh(mesh) -> None:
    """Install `mesh` (a jax.sharding.Mesh or None) as the process mesh."""
    global _mesh, _generation
    _mesh = mesh
    _generation += 1
    for fn in _listeners:
        fn()


def enable_mesh(n_devices: int | None = None) -> None:
    """Build a ``("batch",)`` mesh over the first n jax devices and
    install it."""
    configure_mesh(build_mesh(n_devices))


def disable_mesh() -> None:
    configure_mesh(None)


def active_mesh() -> Mesh | None:
    return _mesh


def mesh_generation() -> int:
    return _generation


def ndev(mesh: Mesh | None = None) -> int:
    """Device count of `mesh` (default: the process mesh; 1 if none)."""
    if mesh is None:
        mesh = _mesh
    return 1 if mesh is None else int(mesh.devices.size)


# -- layout language -------------------------------------------------------

def batch_spec() -> PartitionSpec:
    """Rows sharded over the ``"batch"`` axis."""
    return PartitionSpec(AXIS)


def replicated_spec() -> PartitionSpec:
    return PartitionSpec()


def batch_sharding(mesh: Mesh | None = None) -> NamedSharding:
    """``NamedSharding(mesh, P("batch"))`` — superchunk row layout."""
    return NamedSharding(_mesh if mesh is None else mesh, batch_spec())


def replicated(mesh: Mesh | None = None) -> NamedSharding:
    """``NamedSharding(mesh, P())`` — broadcast state / HBM point blocks."""
    return NamedSharding(_mesh if mesh is None else mesh, replicated_spec())


def chip_device(chip: int, mesh: Mesh | None = None):
    """The jax device backing plane chip index `chip` (modulo the
    device count); None when no mesh is installed — callers then use
    the default device."""
    if mesh is None:
        mesh = _mesh
    if mesh is None:
        return None
    return mesh.devices.flat[chip % int(mesh.devices.size)]


def chip_scope(chip: int, mesh: Mesh | None = None):
    """Place a slot-guarded dispatch section's UNCOMMITTED transfers
    and jit executions on chip `chip`'s device (jax.default_device).
    Committed inputs — replicated HBM blocks, sharded superchunks —
    keep their NamedSharding placement regardless; this steers only the
    host-staged point/one-shot dispatches the scheduler just placed.
    No-op without a mesh."""
    dev = chip_device(chip, mesh)
    if dev is None:
        return contextlib.nullcontext()
    return jax.default_device(dev)


def mesh_fingerprint(mesh: Mesh | None = None, *,
                     process: bool = False) -> tuple:
    """Structural identity of the plane for cache keys: axis layout +
    device count + platform. Two executables compiled under different
    fingerprints never alias. With ``process=True``, fingerprint the
    installed process mesh (the common case for kernel caches keyed
    before a mesh is chosen per dispatch)."""
    if mesh is None and process:
        mesh = _mesh
    if mesh is None:
        return ("host", 1)
    plat = mesh.devices.flat[0].platform
    return (AXIS, int(mesh.devices.size), plat)


# -- compiled-program seams ------------------------------------------------

def shard_map(fn, mesh: Mesh, in_specs, out_specs):
    """shard_map with replication checking off (our kernels mix manually
    replicated scalars with sharded lanes), spanning the jax spelling
    change (check_vma vs the older check_rep)."""
    kwargs = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    try:
        return _shard_map_fn(fn, check_vma=False, **kwargs)
    except TypeError:       # older jax spells it check_rep
        return _shard_map_fn(fn, check_rep=False, **kwargs)


def plane_jit(fn, **kwargs):
    """jit for plane kernels. Modern jax's jit IS pjit — NamedSharding
    inputs drive partitioned compilation directly, and on cpu a
    virtual-device mesh lowers the same way — so this is a plain jit
    today; it exists as the single seam to grow per-backend dispatch
    options (donation policies, compiler flags) without touching every
    kernel. Each wrap registers one `plane`-family compile unit with
    the kernel-profile registry (keyed by the staged function's name +
    the process mesh): plane-stage re-jitting that the executable
    caches should have absorbed shows up as compile churn on one row."""
    from tidb_tpu import profiler
    prof = profiler.profile("plane", getattr(fn, "__name__", "shard"))
    profiler.note_construct(prof, reuse=False)
    return jax.jit(fn, **kwargs)
