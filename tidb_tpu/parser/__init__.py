from tidb_tpu.parser.parser import ParseError, parse, parse_one
from tidb_tpu.parser import ast

__all__ = ["parse", "parse_one", "ParseError", "ast"]
