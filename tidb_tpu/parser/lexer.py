"""SQL lexer.

Reference: /root/reference/parser/lexer.go (hand-written scanner feeding the
goyacc grammar) — here feeding a recursive-descent parser instead. MySQL
dialect essentials: backquoted identifiers, single/double-quoted strings
with '' and \\ escapes, numeric literals (int/decimal/float), line (--, #)
and block comments, multi-char operators (<=, >=, <>, !=, <=>, ||, &&, <<, >>).
"""

from __future__ import annotations

from dataclasses import dataclass
from enum import Enum, auto

__all__ = ["TokenType", "Token", "Lexer", "LexError", "KEYWORDS"]


class LexError(Exception):
    pass


class TokenType(Enum):
    IDENT = auto()
    KEYWORD = auto()
    INT = auto()
    DECIMAL = auto()     # numeric literal with a fraction part
    FLOAT = auto()       # scientific notation
    STRING = auto()
    OP = auto()
    EOF = auto()


KEYWORDS = {
    "SELECT", "FROM", "WHERE", "GROUP", "BY", "HAVING", "ORDER", "LIMIT",
    "OFFSET", "AS", "AND", "OR", "NOT", "XOR", "IN", "BETWEEN", "LIKE",
    "IS", "NULL", "TRUE", "FALSE", "DISTINCT", "ALL", "ASC", "DESC",
    "JOIN", "INNER", "LEFT", "RIGHT", "FULL", "OUTER", "CROSS", "ON",
    "USING", "UNION", "EXISTS", "ANY", "CASE", "WHEN", "THEN", "ELSE",
    "END", "CAST", "CONVERT", "DIV", "MOD", "INTERVAL",
    "INSERT", "INTO", "VALUES", "VALUE", "REPLACE", "UPDATE", "SET",
    "DELETE", "DUPLICATE", "KEY", "DEFAULT",
    "CREATE", "TABLE", "DATABASE", "SCHEMA", "INDEX", "UNIQUE", "PRIMARY",
    "DROP", "ALTER", "ADD", "COLUMN", "TRUNCATE", "RENAME", "TO", "MODIFY",
    "CHANGE", "CONSTRAINT", "REFERENCES", "FOREIGN", "AUTO_INCREMENT",
    "IF", "IFNULL", "COALESCE", "NULLIF",
    "INT", "INTEGER", "BIGINT", "SMALLINT", "TINYINT", "MEDIUMINT",
    "FLOAT", "DOUBLE", "REAL", "DECIMAL", "NUMERIC", "CHAR", "VARCHAR",
    "TEXT", "BLOB", "DATE", "DATETIME", "TIMESTAMP", "TIME", "YEAR",
    "BOOL", "BOOLEAN", "UNSIGNED", "SIGNED", "ZEROFILL", "BINARY",
    "PRECISION", "VARYING",
    "BEGIN", "START", "TRANSACTION", "COMMIT", "ROLLBACK",
    "USE", "SHOW", "DATABASES", "TABLES", "COLUMNS", "FIELDS", "EXPLAIN",
    "DESCRIBE", "ANALYZE", "ADMIN", "CHECK",
    "GLOBAL", "SESSION", "VARIABLES", "STATUS", "ENGINES", "ENGINE",
    "CHARSET", "COLLATE", "COLLATION", "COMMENT", "FIRST", "AFTER",
    "GRANT", "REVOKE", "PRIVILEGES", "IDENTIFIED", "WITH", "OPTION", "USER",
    "FOR", "FORCE", "IGNORE", "LOW_PRIORITY", "HIGH_PRIORITY", "QUICK",
    "PARTITION", "TEMPORARY", "EXTENDED",
    "PREPARE", "EXECUTE", "DEALLOCATE",
}

# Words with meaning only inside LOAD DATA / SPLIT TABLE clauses. They
# stay ordinary identifiers everywhere else (reserving them would break
# queries using e.g. `data` or `at` as column/alias names); the parser
# matches them by value via try_word/expect_word.
NON_RESERVED = {
    "LOAD", "DATA", "INFILE", "TERMINATED", "ENCLOSED", "ESCAPED",
    "LINES", "OPTIONALLY", "STARTING", "SPLIT", "AT", "REGIONS", "LOCAL",
    "KILL", "TIDB", "CONNECTION", "QUERY", "DO", "FLUSH", "ESCAPE",
    # ALTER/SET/SHOW long tail (keyword meaning only in those clauses)
    "DISABLE", "ENABLE", "KEYS", "READ", "ONLY", "ISOLATION", "LEVEL",
    "BINARY", "CHARACTER", "FULLTEXT", "TRANSACTION", "PASSWORD",
    "TABLES", "STATS", "NO_WRITE_TO_BINLOG", "SHARE", "MODE",
    "DISTINCTROW", "CHARSET", "LOCK", "VIEW", "JOBS", "CANCEL",
    "REPLACE", "ALGORITHM", "DEFINER", "SQL", "SECURITY", "CASCADED",
    "OPTION", "STRAIGHT_JOIN", "USING",
    # TRACE [FORMAT='row'|'json'] <stmt> (session._exec_trace): both
    # words stay ordinary identifiers outside that statement head
    "TRACE", "FORMAT",
}


@dataclass
class Token:
    tp: TokenType
    val: str
    pos: int

    def is_kw(self, kw: str) -> bool:
        return self.tp == TokenType.KEYWORD and self.val == kw

    def __repr__(self):
        return f"{self.tp.name}({self.val})"


_TWO_CHAR_OPS = {"<=", ">=", "<>", "!=", "||", "&&", "<<", ">>", ":="}
_THREE_CHAR_OPS = {"<=>"}
_ONE_CHAR_OPS = set("+-*/%(),.;=<>!~&|^@?")


class Lexer:
    def __init__(self, sql: str):
        self.sql = sql
        self.pos = 0
        self.n = len(sql)

    def tokens(self) -> list[Token]:
        out = []
        while True:
            t = self._next()
            out.append(t)
            if t.tp == TokenType.EOF:
                return out

    def _peek(self, k: int = 0) -> str:
        p = self.pos + k
        return self.sql[p] if p < self.n else ""

    def _next(self) -> Token:
        self._skip_space_and_comments()
        if self.pos >= self.n:
            return Token(TokenType.EOF, "", self.pos)
        c = self.sql[self.pos]
        start = self.pos
        if c in "xX" and self._peek(1) == "'":
            return self._hex_literal(start)          # X'0a'
        if c in "bB" and self._peek(1) == "'":
            return self._bit_literal(start)          # b'1010'
        if c in "nN" and self._peek(1) == "'":
            self.pos += 1                            # N'...' national str
            return self._string(self.pos, "'")
        if c == "0" and self._peek(1) in "xX" and \
                self._is_hex(self._peek(2)):
            return self._hex0x_literal(start)        # 0x0a
        if c.isdigit() or (c == "." and self._peek(1).isdigit()):
            return self._number(start)
        if c.isalpha() or c == "_":
            return self._ident(start)
        if c == "`":
            return self._quoted_ident(start)
        if c in ("'", '"'):
            return self._string(start, c)
        return self._op(start)

    def _skip_space_and_comments(self):
        while self.pos < self.n:
            c = self.sql[self.pos]
            if c.isspace():
                self.pos += 1
            elif c == "-" and self._peek(1) == "-" and \
                    (self._peek(2) in ("", " ", "\t", "\n")):
                while self.pos < self.n and self.sql[self.pos] != "\n":
                    self.pos += 1
            elif c == "#":
                while self.pos < self.n and self.sql[self.pos] != "\n":
                    self.pos += 1
            elif c == "/" and self._peek(1) == "*":
                end = self.sql.find("*/", self.pos + 2)
                if end < 0:
                    raise LexError(f"unterminated comment at {self.pos}")
                self.pos = end + 2
            else:
                return

    @staticmethod
    def _is_hex(c: str) -> bool:
        return bool(c) and c in "0123456789abcdefABCDEF"

    def _hex_literal(self, start: int) -> Token:
        """X'0a' -> INT token (MySQL hex literals act as numbers in
        numeric context; string-context binary semantics are out of
        scope — docs/DEVIATIONS.md)."""
        end = self.sql.find("'", start + 2)
        if end < 0:
            raise LexError(f"unterminated hex literal at {start}")
        digits = self.sql[start + 2:end]
        if digits and not all(self._is_hex(c) for c in digits):
            raise LexError(f"bad hex literal at {start}")
        self.pos = end + 1
        return Token(TokenType.INT, str(int(digits or "0", 16)), start)

    def _bit_literal(self, start: int) -> Token:
        end = self.sql.find("'", start + 2)
        if end < 0:
            raise LexError(f"unterminated bit literal at {start}")
        digits = self.sql[start + 2:end]
        if digits and not all(c in "01" for c in digits):
            raise LexError(f"bad bit literal at {start}")
        self.pos = end + 1
        return Token(TokenType.INT, str(int(digits or "0", 2)), start)

    def _hex0x_literal(self, start: int) -> Token:
        self.pos = start + 2
        while self.pos < self.n and self._is_hex(self.sql[self.pos]):
            self.pos += 1
        return Token(TokenType.INT,
                     str(int(self.sql[start + 2:self.pos], 16)), start)

    def _number(self, start: int) -> Token:
        has_dot = has_exp = False
        while self.pos < self.n:
            c = self.sql[self.pos]
            if c.isdigit():
                self.pos += 1
            elif c == "." and not has_dot and not has_exp:
                # "1.e3" / "1.5" ok; but "1..2" stops
                has_dot = True
                self.pos += 1
            elif c in "eE" and not has_exp and self.pos + 1 < self.n and \
                    (self.sql[self.pos + 1].isdigit() or
                     self.sql[self.pos + 1] in "+-"):
                has_exp = True
                self.pos += 1
                if self.sql[self.pos] in "+-":
                    self.pos += 1
            else:
                break
        text = self.sql[start:self.pos]
        if has_exp:
            return Token(TokenType.FLOAT, text, start)
        if has_dot:
            return Token(TokenType.DECIMAL, text, start)
        return Token(TokenType.INT, text, start)

    def _ident(self, start: int) -> Token:
        while self.pos < self.n and (self.sql[self.pos].isalnum() or
                                     self.sql[self.pos] in "_$"):
            self.pos += 1
        text = self.sql[start:self.pos]
        up = text.upper()
        if up in KEYWORDS:
            return Token(TokenType.KEYWORD, up, start)
        return Token(TokenType.IDENT, text, start)

    def _quoted_ident(self, start: int) -> Token:
        self.pos += 1
        out = []
        while self.pos < self.n:
            c = self.sql[self.pos]
            if c == "`":
                if self._peek(1) == "`":
                    out.append("`")
                    self.pos += 2
                    continue
                self.pos += 1
                return Token(TokenType.IDENT, "".join(out), start)
            out.append(c)
            self.pos += 1
        raise LexError(f"unterminated identifier at {start}")

    def _string(self, start: int, quote: str) -> Token:
        self.pos += 1
        out = []
        while self.pos < self.n:
            c = self.sql[self.pos]
            if c == "\\" and self.pos + 1 < self.n:
                nxt = self.sql[self.pos + 1]
                esc = {"n": "\n", "t": "\t", "r": "\r", "0": "\0",
                       "\\": "\\", "'": "'", '"': '"', "%": "\\%",
                       "_": "\\_"}.get(nxt, nxt)
                out.append(esc)
                self.pos += 2
                continue
            if c == quote:
                if self._peek(1) == quote:   # '' escape
                    out.append(quote)
                    self.pos += 2
                    continue
                self.pos += 1
                return Token(TokenType.STRING, "".join(out), start)
            out.append(c)
            self.pos += 1
        raise LexError(f"unterminated string at {start}")

    def _op(self, start: int) -> Token:
        three = self.sql[self.pos:self.pos + 3]
        if three in _THREE_CHAR_OPS:
            self.pos += 3
            return Token(TokenType.OP, three, start)
        two = self.sql[self.pos:self.pos + 2]
        if two in _TWO_CHAR_OPS:
            self.pos += 2
            return Token(TokenType.OP, two, start)
        c = self.sql[self.pos]
        if c in _ONE_CHAR_OPS:
            self.pos += 1
            return Token(TokenType.OP, c, start)
        raise LexError(f"unexpected character {c!r} at {self.pos}")
