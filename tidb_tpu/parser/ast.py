"""AST node hierarchy.

Reference: /root/reference/ast/ — Node/ExprNode/StmtNode (ast/ast.go:29-94),
DML nodes (ast/dml.go), DDL nodes (ast/ddl.go). Dataclasses instead of the
reference's visitor-heavy interfaces; the planner pattern-matches on types.
Unresolved names live here; the planner resolves them into
tidb_tpu.expression columnar trees.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Optional

from tidb_tpu.sqltypes import FieldType

__all__ = [
    "Node", "ExprNode", "StmtNode",
    "Literal", "ColName", "Star", "BinaryOp", "UnaryOp", "FuncCall",
    "AggregateCall", "CaseExpr", "InExpr", "BetweenExpr", "LikeExpr",
    "IsNullExpr", "CastExpr", "ExistsSubquery", "SubqueryExpr",
    "QuantSubquery", "RowExpr",
    "VariableExpr", "DefaultExpr", "ParamMarker",
    "JoinType", "TableSource", "Join", "SubqueryTable",
    "SelectField", "ByItem", "SelectStmt", "UnionStmt",
    "InsertStmt", "UpdateStmt", "DeleteStmt", "Assignment",
    "ColumnDef", "IndexDef", "CreateTableStmt", "CreateDatabaseStmt",
    "CreateIndexStmt", "DropTableStmt", "DropDatabaseStmt", "DropIndexStmt",
    "AlterTableStmt", "AlterSpec", "TruncateTableStmt", "RenameTableStmt",
    "UseStmt", "BeginStmt", "CommitStmt", "RollbackStmt",
    "SetStmt", "VarAssignment", "ShowStmt", "ExplainStmt", "AnalyzeStmt",
    "AdminStmt", "PrepareStmt", "ExecuteStmt", "DeallocateStmt",
    "LoadDataStmt", "SplitTableStmt", "KillStmt", "DoStmt", "FlushStmt",
]


class Node:
    pass


class ExprNode(Node):
    pass


class StmtNode(Node):
    pass


# ---------------------------------------------------------------------------
# Expressions

@dataclass
class Literal(ExprNode):
    value: Any               # python value; Decimal for DECIMAL literals
    ft: Optional[FieldType] = None


@dataclass
class ColName(ExprNode):
    name: str
    table: str = ""
    db: str = ""

    def __repr__(self):
        parts = [p for p in (self.db, self.table, self.name) if p]
        return ".".join(parts)


@dataclass
class Star(ExprNode):
    table: str = ""          # t.* form


@dataclass
class BinaryOp(ExprNode):
    op: str                  # '+', '-', '*', '/', 'DIV', '%', '=', '<', ...
    left: ExprNode
    right: ExprNode


@dataclass
class UnaryOp(ExprNode):
    op: str                  # '-', '+', 'NOT', '~'
    operand: ExprNode


@dataclass
class FuncCall(ExprNode):
    name: str                # uppercased
    args: list = field(default_factory=list)


@dataclass
class AggregateCall(ExprNode):
    name: str                # COUNT/SUM/AVG/MIN/MAX/GROUP_CONCAT...
    args: list = field(default_factory=list)   # empty for COUNT(*)
    distinct: bool = False
    star: bool = False
    sep: str = ","           # GROUP_CONCAT ... SEPARATOR '...'


@dataclass
class CaseExpr(ExprNode):
    operand: Optional[ExprNode]          # CASE x WHEN ... / CASE WHEN ...
    when_clauses: list = field(default_factory=list)  # [(cond, result)]
    else_clause: Optional[ExprNode] = None


@dataclass
class InExpr(ExprNode):
    expr: ExprNode
    items: list = field(default_factory=list)  # exprs, or a SubqueryExpr
    negated: bool = False


@dataclass
class BetweenExpr(ExprNode):
    expr: ExprNode
    low: ExprNode
    high: ExprNode
    negated: bool = False


@dataclass
class LikeExpr(ExprNode):
    expr: ExprNode
    pattern: ExprNode
    negated: bool = False
    escape: str = "\\"       # LIKE ... ESCAPE 'c'; "" = no escape char


@dataclass
class IsNullExpr(ExprNode):
    expr: ExprNode
    negated: bool = False


@dataclass
class CastExpr(ExprNode):
    expr: ExprNode
    ft: FieldType


@dataclass
class SubqueryExpr(ExprNode):
    select: "SelectStmt" = None


@dataclass
class QuantSubquery(ExprNode):
    """expr <cmp> ANY/SOME/ALL (SELECT ...)."""
    expr: ExprNode = None
    op: str = "="            # comparison operator token
    quant: str = "any"       # "any" (SOME == ANY) | "all"
    select: "SelectStmt" = None


@dataclass
class ExistsSubquery(ExprNode):
    select: "SelectStmt" = None
    negated: bool = False


@dataclass
class RowExpr(ExprNode):
    items: list = field(default_factory=list)


@dataclass
class VariableExpr(ExprNode):
    name: str
    is_global: bool = False
    is_system: bool = False


@dataclass
class VarAssignExpr(ExprNode):
    """@v := expr in expression position (SELECT @a := 1)."""
    name: str = ""
    value: ExprNode | None = None


@dataclass
class DefaultExpr(ExprNode):
    pass              # bare DEFAULT; DEFAULT(col) parses as FuncCall


@dataclass
class ParamMarker(ExprNode):
    index: int = 0
    # bound by the session before planning a prepared execution
    value: object = None
    bound: bool = False


# ---------------------------------------------------------------------------
# Table references

class JoinType(Enum):
    INNER = "inner"
    LEFT = "left"
    RIGHT = "right"
    CROSS = "cross"


@dataclass
class TableSource(Node):
    name: str
    db: str = ""
    alias: str = ""
    # (kind, [index names]) with kind USE|IGNORE|FORCE
    index_hints: list = field(default_factory=list)

    @property
    def ref_name(self) -> str:
        return self.alias or self.name


@dataclass
class SubqueryTable(Node):
    select: "SelectStmt" = None
    alias: str = ""


@dataclass
class Join(Node):
    left: Node
    right: Node
    tp: JoinType = JoinType.CROSS
    on: Optional[ExprNode] = None
    using: list = field(default_factory=list)
    natural: bool = False    # NATURAL JOIN: USING(all common names)


# ---------------------------------------------------------------------------
# SELECT

@dataclass
class SelectField(Node):
    expr: ExprNode           # Star for '*'
    alias: str = ""


@dataclass
class ByItem(Node):
    expr: ExprNode
    desc: bool = False


@dataclass
class SelectStmt(StmtNode):
    fields: list = field(default_factory=list)        # [SelectField]
    from_clause: Optional[Node] = None                # TableSource/Join/None
    where: Optional[ExprNode] = None
    group_by: list = field(default_factory=list)      # [ByItem]
    having: Optional[ExprNode] = None
    order_by: list = field(default_factory=list)      # [ByItem]
    limit: Optional[int] = None
    offset: int = 0
    distinct: bool = False
    for_update: bool = False


@dataclass
class UnionStmt(StmtNode):
    selects: list = field(default_factory=list)
    # alls[i] is True iff the connector before selects[i+1] was UNION ALL
    # (per-branch, as in MySQL; a single sticky flag would make one ALL
    # poison every branch)
    alls: list = field(default_factory=list)
    order_by: list = field(default_factory=list)
    limit: Optional[int] = None
    offset: int = 0


# ---------------------------------------------------------------------------
# DML

@dataclass
class Assignment(Node):
    col: ColName
    expr: ExprNode


@dataclass
class InsertStmt(StmtNode):
    table: TableSource = None
    columns: list = field(default_factory=list)       # [str]
    values: list = field(default_factory=list)        # [[ExprNode]]
    select: Optional[SelectStmt] = None
    on_duplicate: list = field(default_factory=list)  # [Assignment]
    is_replace: bool = False
    ignore: bool = False


@dataclass
class UpdateStmt(StmtNode):
    table: Node = None                                # TableSource or Join
    assignments: list = field(default_factory=list)   # [Assignment]
    where: Optional[ExprNode] = None
    order_by: list = field(default_factory=list)
    limit: Optional[int] = None


@dataclass
class DeleteStmt(StmtNode):
    table: TableSource = None
    where: Optional[ExprNode] = None
    order_by: list = field(default_factory=list)
    limit: Optional[int] = None
    # multi-table form (ref: ast/dml.go DeleteStmt.IsMultiTable):
    # DELETE t1, t2 FROM <refs> / DELETE FROM t1, t2 USING <refs>
    targets: list = field(default_factory=list)   # [TableSource]
    refs: Optional[Node] = None                   # join tree


# ---------------------------------------------------------------------------
# DDL

@dataclass
class ColumnDef(Node):
    name: str
    ft: FieldType
    default: Optional[ExprNode] = None
    has_default: bool = False
    comment: str = ""
    is_primary: bool = False          # inline PRIMARY KEY
    is_unique: bool = False           # inline UNIQUE
    auto_increment: bool = False
    # an explicit column COLLATE wins over the table default, even when
    # it names the default collation (utf8mb4_bin)
    explicit_collation: bool = False


@dataclass
class IndexDef(Node):
    name: str
    columns: list = field(default_factory=list)       # [str]
    unique: bool = False
    primary: bool = False


@dataclass
class CreateTableStmt(StmtNode):
    table: TableSource = None
    columns: list = field(default_factory=list)       # [ColumnDef]
    indexes: list = field(default_factory=list)       # [IndexDef]
    if_not_exists: bool = False
    options: dict = field(default_factory=dict)       # engine/charset/comment
    like_table: Optional[TableSource] = None          # CREATE TABLE a LIKE b


@dataclass
class CreateDatabaseStmt(StmtNode):
    name: str = ""
    if_not_exists: bool = False


@dataclass
class CreateIndexStmt(StmtNode):
    index_name: str = ""
    table: TableSource = None
    columns: list = field(default_factory=list)
    unique: bool = False


@dataclass
class DropTableStmt(StmtNode):
    tables: list = field(default_factory=list)        # [TableSource]
    if_exists: bool = False


@dataclass
class DropDatabaseStmt(StmtNode):
    name: str = ""
    if_exists: bool = False


@dataclass
class DropIndexStmt(StmtNode):
    index_name: str = ""
    table: TableSource = None
    if_exists: bool = False


@dataclass
class AlterSpec(Node):
    tp: str                  # add_column(s)/drop_column/add_index/
    #                          drop_index/modify_column/change_column/
    #                          rename/set_default/drop_default/noop
    column: Optional[ColumnDef] = None
    columns: Optional[list] = None     # ADD COLUMN (a ..., b ...)
    index: Optional[IndexDef] = None
    name: str = ""           # drop target / rename target
    position: str = ""       # FIRST / AFTER <col>
    after_col: str = ""
    default: Optional[ExprNode] = None  # SET DEFAULT value
    new_db: str = ""         # RENAME to another database


@dataclass
class AlterTableStmt(StmtNode):
    table: TableSource = None
    specs: list = field(default_factory=list)


@dataclass
class TruncateTableStmt(StmtNode):
    table: TableSource = None


@dataclass
class RenameTableStmt(StmtNode):
    pairs: list = field(default_factory=list)         # [(old TS, new TS)]


# ---------------------------------------------------------------------------
# Session / admin

@dataclass
class UseStmt(StmtNode):
    db: str = ""


@dataclass
class BeginStmt(StmtNode):
    pass


@dataclass
class CommitStmt(StmtNode):
    pass


@dataclass
class RollbackStmt(StmtNode):
    pass


@dataclass
class VarAssignment(Node):
    name: str
    value: ExprNode = None
    is_global: bool = False
    is_system: bool = False


@dataclass
class SetStmt(StmtNode):
    assignments: list = field(default_factory=list)


@dataclass
class ShowStmt(StmtNode):
    tp: str = ""             # databases/tables/columns/variables/create_table
    table: Optional[TableSource] = None
    db: str = ""
    pattern: Optional[str] = None    # LIKE '...'
    where: Optional[ExprNode] = None
    is_global: bool = False
    full: bool = False       # SHOW FULL PROCESSLIST: untruncated Info


@dataclass
class ExplainStmt(StmtNode):
    stmt: StmtNode = None
    analyze: bool = False    # EXPLAIN ANALYZE: execute + actual stats


@dataclass
class TraceStmt(StmtNode):
    """TRACE [FORMAT='row'|'json'] <stmt>: execute the inner statement
    with forced trace retention and return its span tree (ref: the
    reference's TRACE statement over its per-statement trace trees)."""
    stmt: StmtNode = None
    format: str = "row"      # 'row' (indented tree rows) or 'json'


@dataclass
class AnalyzeStmt(StmtNode):
    tables: list = field(default_factory=list)
    index_names: Optional[list] = None   # ANALYZE ... INDEX [names]


@dataclass
class PrepareStmt(StmtNode):
    name: str = ""
    sql: str = ""                  # the statement text to prepare
    from_var: str | None = None    # PREPARE s FROM @v


@dataclass
class ExecuteStmt(StmtNode):
    name: str = ""
    using: list = field(default_factory=list)   # user variable names


@dataclass
class DeallocateStmt(StmtNode):
    name: str = ""


@dataclass
class AdminStmt(StmtNode):
    tp: str = ""             # show_ddl / check_table / cancel_ddl_jobs
    tables: list = field(default_factory=list)
    job_ids: list = field(default_factory=list)


@dataclass
class LoadDataStmt(StmtNode):
    """LOAD DATA [LOCAL] INFILE (ref: ast/dml.go LoadDataStmt,
    executor/write.go:1373 LoadData)."""
    path: str = ""
    local: bool = False
    table: TableSource = None
    columns: list = field(default_factory=list)   # [str]; empty = all
    fields_terminated: str = "\t"
    fields_enclosed: str = ""                     # "" = none
    fields_escaped: str = "\\"
    lines_starting: str = ""
    lines_terminated: str = "\n"
    ignore_lines: int = 0
    dup_mode: str = "error"                       # error / ignore / replace


@dataclass
class DoStmt(StmtNode):
    """DO expr[, ...]: evaluate and discard (ref: ast/misc.go DoStmt;
    executor/simple.go)."""
    exprs: list = field(default_factory=list)


@dataclass
class FlushStmt(StmtNode):
    """FLUSH PRIVILEGES|STATUS|TABLES (ref: ast/misc.go FlushStmt;
    executor/simple.go:311 executeFlush)."""
    tp: str = ""


@dataclass
class KillStmt(StmtNode):
    """KILL [TIDB] [CONNECTION | QUERY] id (ref: ast/misc.go:341
    KillStmt — query_only leaves the connection intact)."""
    conn_id: int = 0
    query_only: bool = False


@dataclass
class SplitTableStmt(StmtNode):
    """SPLIT TABLE t AT (v)[,(v)...] | SPLIT TABLE t REGIONS n
    (ref: store/tikv/split_region.go:29 SplitRegion RPC; mocktikv
    cluster.go:276 Split/SplitTable)."""
    table: TableSource = None
    at_values: list = field(default_factory=list)   # [ExprNode literals]
    regions: int = 0                                # REGIONS n form


# -- account management (ref: ast/misc.go CreateUserStmt/GrantStmt) ----------

@dataclass
class UserSpec:
    user: str = ""
    host: str = "%"
    password: str | None = None    # IDENTIFIED BY (plaintext at parse time)


@dataclass
class CreateUserStmt(StmtNode):
    users: list = field(default_factory=list)      # [UserSpec]
    if_not_exists: bool = False


@dataclass
class CreateViewStmt(StmtNode):
    """Parsed for parity with ast/ddl.go CreateViewStmt; execution
    rejects it (the reference's planner does too: no view support)."""

    view: TableSource = None
    columns: list = field(default_factory=list)
    select: Optional[SelectStmt] = None
    or_replace: bool = False


@dataclass
class DropViewStmt(StmtNode):
    """Views are unimplemented; DROP VIEW IF EXISTS no-ops (migration
    scripts), otherwise errors."""

    tables: list = field(default_factory=list)
    if_exists: bool = False


@dataclass
class DropStatsStmt(StmtNode):
    """DROP STATS t (ref: parser.y DropStatsStmt)."""

    table: TableSource = None


@dataclass
class SetPasswordStmt(StmtNode):
    """SET PASSWORD [FOR user] = 'pw' (ref: parser.y SetPwdStmt)."""

    user: Optional["UserSpec"] = None   # None = the current user
    password: str = ""


@dataclass
class DropUserStmt(StmtNode):
    users: list = field(default_factory=list)      # [UserSpec]
    if_exists: bool = False


@dataclass
class GrantStmt(StmtNode):
    privs: list = field(default_factory=list)      # upper priv names / "ALL"
    db: str = "*"                                  # "*" = global
    table: str = "*"                               # "*" = whole db
    users: list = field(default_factory=list)      # [UserSpec]


@dataclass
class RevokeStmt(StmtNode):
    privs: list = field(default_factory=list)
    db: str = "*"
    table: str = "*"
    users: list = field(default_factory=list)
