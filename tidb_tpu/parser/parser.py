"""Recursive-descent SQL parser (MySQL dialect subset).

Reference: /root/reference/parser/parser.y (6,404-line goyacc LALR grammar).
Deliberately NOT a grammar port (SURVEY.md §7 stage 4: "do not rebuild the
6.4k-line grammar; grow it feature-by-feature"): a hand-written
Pratt/recursive-descent parser covering the SQL surface the framework
executes — TPC-H-class SELECT (joins, subqueries, aggregates, CASE),
DML, DDL, txn control, SET/SHOW/EXPLAIN/ANALYZE/ADMIN.
"""

from __future__ import annotations

import decimal

from tidb_tpu import sqltypes as st
from tidb_tpu.parser import ast
from tidb_tpu.parser.lexer import (Lexer, NON_RESERVED, Token,
                                   TokenType)

__all__ = ["parse", "parse_one", "ParseError"]


class ParseError(Exception):
    def __init__(self, msg: str, tok: Token | None = None):
        if tok is not None:
            msg = f"{msg} near {tok.val!r} (pos {tok.pos})"
        super().__init__(msg)


def parse(sql: str) -> list[ast.StmtNode]:
    """Parse a semicolon-separated statement list.
    Ref: parser.Parse (parser/yy_parser.go:88) -> []ast.StmtNode."""
    toks = Lexer(sql).tokens()
    p = Parser(toks)
    stmts = []
    while not p.at_eof():
        if p.try_op(";"):
            continue
        stmts.append(p.statement())
        if not p.at_eof():
            p.expect_op(";")
    return stmts


def parse_one(sql: str) -> ast.StmtNode:
    stmts = parse(sql)
    if len(stmts) != 1:
        raise ParseError(f"expected one statement, got {len(stmts)}")
    return stmts[0]


_AGG_FUNCS = {"COUNT", "SUM", "AVG", "MIN", "MAX", "GROUP_CONCAT",
              "BIT_AND", "BIT_OR", "BIT_XOR"}

_CMP_OPS = {"=", "<", "<=", ">", ">=", "<>", "!=", "<=>"}


MAX_EXPR_DEPTH = 64  # explicit cap: clean error instead of RecursionError


class Parser:
    def __init__(self, toks: list[Token]):
        self.toks = toks
        self.i = 0
        self.depth = 0

    # -- token helpers -------------------------------------------------------

    def peek(self, k: int = 0) -> Token:
        j = min(self.i + k, len(self.toks) - 1)
        return self.toks[j]

    def next(self) -> Token:
        t = self.toks[self.i]
        if t.tp != TokenType.EOF:
            self.i += 1
        return t

    def at_eof(self) -> bool:
        return self.peek().tp == TokenType.EOF

    def try_kw(self, *kws: str) -> bool:
        t = self.peek()
        if t.tp == TokenType.KEYWORD and t.val in kws:
            self.next()
            return True
        return False

    def expect_kw(self, kw: str) -> None:
        if not self.try_kw(kw):
            raise ParseError(f"expected {kw}", self.peek())

    # word helpers: match a KEYWORD *or* IDENT by (upper-cased) value —
    # for MySQL's many non-reserved words (ISOLATION, LOCAL, DISABLE...)
    def peek_word(self, k: int = 0) -> str:
        t = self.peek(k)
        return t.val.upper() if t.tp in (TokenType.KEYWORD,
                                         TokenType.IDENT) else ""

    # non-reserved words (lexer.NON_RESERVED): keyword meaning only in
    # LOAD DATA / SPLIT TABLE clauses, plain identifiers elsewhere
    def try_word(self, *words: str) -> bool:
        unknown = [w for w in words if w not in NON_RESERVED]
        if unknown:   # programming-error guard: keep the registry honest
            raise ParseError(
                f"internal: {unknown} missing from lexer.NON_RESERVED")
        t = self.peek()
        if t.tp in (TokenType.IDENT, TokenType.KEYWORD) and \
                t.val.upper() in words:
            self.next()
            return True
        return False

    def expect_word(self, word: str) -> None:
        if not self.try_word(word):
            raise ParseError(f"expected {word}", self.peek())

    def try_op(self, op: str) -> bool:
        t = self.peek()
        if t.tp == TokenType.OP and t.val == op:
            self.next()
            return True
        return False

    def expect_op(self, op: str) -> None:
        if not self.try_op(op):
            raise ParseError(f"expected {op!r}", self.peek())

    def ident(self) -> str:
        t = self.peek()
        if t.tp == TokenType.IDENT:
            self.next()
            return t.val
        # many keywords double as identifiers in practice
        if t.tp == TokenType.KEYWORD and t.val not in (
                "SELECT", "FROM", "WHERE", "AND", "OR", "NOT"):
            self.next()
            return t.val.lower()
        raise ParseError("expected identifier", t)

    # -- statements ----------------------------------------------------------

    def statement(self) -> ast.StmtNode:
        t = self.peek()
        if t.tp == TokenType.IDENT and \
                t.val.upper() in ("LOAD", "SPLIT", "KILL", "DO",
                                  "FLUSH", "TRACE"):
            # non-reserved statement heads (see lexer.NON_RESERVED)
            head = t.val.upper()
            if head == "LOAD":
                return self.load_data()
            if head == "SPLIT":
                return self.split_table()
            if head == "KILL":
                return self.kill_stmt()
            if head == "TRACE":
                return self.trace_stmt()
            if head == "DO":
                self.next()
                exprs = [self.expr()]
                while self.try_op(","):
                    exprs.append(self.expr())
                return ast.DoStmt(exprs=exprs)
            self.next()                      # FLUSH
            # FLUSH [NO_WRITE_TO_BINLOG|LOCAL] TABLES [t, ...]
            #       [WITH READ LOCK] / PRIVILEGES / STATUS ...
            self.try_word("NO_WRITE_TO_BINLOG", "LOCAL")
            kind = self.ident().lower()
            if kind in ("tables", "table"):
                kind = "tables"
                while self.peek().tp == TokenType.IDENT:
                    self.ident()
                    if not self.try_op(","):
                        break
                if self.try_kw("WITH"):
                    self.expect_word("READ")
                    self.expect_word("LOCK")
            return ast.FlushStmt(tp=kind)
        if t.tp != TokenType.KEYWORD and not (t.tp == TokenType.OP and
                                              t.val == "("):
            raise ParseError("expected statement", t)
        kw = t.val
        if kw == "SELECT" or kw == "(":
            return self.select_or_union()
        if kw in ("INSERT", "REPLACE"):
            return self.insert()
        if kw == "UPDATE":
            return self.update()
        if kw == "DELETE":
            return self.delete()
        if kw == "CREATE":
            return self.create()
        if kw == "DROP":
            return self.drop()
        if kw == "ALTER":
            return self.alter()
        if kw == "TRUNCATE":
            self.next()
            self.try_kw("TABLE")
            return ast.TruncateTableStmt(table=self.table_name())
        if kw == "RENAME":
            return self.rename()
        if kw == "USE":
            self.next()
            return ast.UseStmt(db=self.ident())
        if kw == "BEGIN":
            self.next()
            return ast.BeginStmt()
        if kw == "START":
            self.next()
            self.expect_kw("TRANSACTION")
            return ast.BeginStmt()
        if kw == "COMMIT":
            self.next()
            return ast.CommitStmt()
        if kw == "ROLLBACK":
            self.next()
            return ast.RollbackStmt()
        if kw == "SET":
            return self.set_stmt()
        if kw == "SHOW":
            return self.show()
        if kw in ("EXPLAIN", "DESCRIBE"):
            self.next()
            if self.peek().tp in (TokenType.IDENT,) or (
                    self.peek().tp == TokenType.KEYWORD and
                    self.peek().val not in ("SELECT", "INSERT", "UPDATE",
                                            "DELETE", "EXTENDED",
                                            "ANALYZE")):
                # DESCRIBE <table>
                return ast.ShowStmt(tp="columns", table=self.table_name())
            analyze = bool(self.try_kw("ANALYZE"))
            self.try_kw("EXTENDED")
            return ast.ExplainStmt(stmt=self.statement(), analyze=analyze)
        if kw == "PREPARE":
            self.next()
            name = self.ident()
            self.expect_kw("FROM")
            if self.try_op("@"):
                # PREPARE s FROM @v: text read from the user variable at
                # execution time (session layer)
                return ast.PrepareStmt(name=name, sql="",
                                       from_var="@" + self.ident())
            tok = self.next()
            if tok.tp != TokenType.STRING:
                raise ParseError("PREPARE requires a string literal")
            return ast.PrepareStmt(name=name, sql=tok.val)
        if kw == "EXECUTE":
            self.next()
            name = self.ident()
            using = []
            if self.try_kw("USING"):
                while True:
                    if not self.try_op("@"):
                        raise ParseError("EXECUTE USING takes @variables")
                    using.append("@" + self.ident())
                    if not self.try_op(","):
                        break
            return ast.ExecuteStmt(name=name, using=using)
        if kw == "DEALLOCATE":
            self.next()
            self.expect_kw("PREPARE")
            return ast.DeallocateStmt(name=self.ident())
        if kw == "ANALYZE":
            self.next()
            self.expect_kw("TABLE")
            tables = [self.table_name()]
            while self.try_op(","):
                tables.append(self.table_name())
            idx_names = None
            if self.try_kw("INDEX"):
                # ANALYZE TABLE t INDEX [a, b]: restrict to index stats
                idx_names = []
                while self.peek().tp == TokenType.IDENT:
                    idx_names.append(self.ident())
                    if not self.try_op(","):
                        break
            return ast.AnalyzeStmt(tables=tables, index_names=idx_names)
        if kw == "GRANT":
            return self.grant_revoke(is_grant=True)
        if kw == "REVOKE":
            return self.grant_revoke(is_grant=False)
        if kw == "ADMIN":
            self.next()
            if self.try_kw("SHOW"):
                if self.peek().tp == TokenType.IDENT and \
                        self.peek().val.upper() == "DDL":
                    self.next()
                    if self.peek().tp == TokenType.IDENT and \
                            self.peek().val.upper() == "JOBS":
                        self.next()
                        return ast.AdminStmt(tp="show_ddl_jobs")
                return ast.AdminStmt(tp="show_ddl")
            if self.try_word("CANCEL"):
                # ADMIN CANCEL DDL JOBS id [, id]
                if self.peek_word() == "DDL":
                    self.next()
                self.expect_word("JOBS")
                ids = [self._int_lit()]
                while self.try_op(","):
                    ids.append(self._int_lit())
                return ast.AdminStmt(tp="cancel_ddl_jobs", job_ids=ids)
            self.expect_kw("CHECK")
            self.expect_kw("TABLE")
            tables = [self.table_name()]
            while self.try_op(","):
                tables.append(self.table_name())
            return ast.AdminStmt(tp="check_table", tables=tables)
        raise ParseError("unsupported statement", t)

    # -- LOAD DATA / SPLIT ---------------------------------------------------

    def _str_lit(self) -> str:
        tok = self.next()
        if tok.tp != TokenType.STRING:
            raise ParseError("expected string literal", tok)
        return tok.val

    def load_data(self) -> ast.LoadDataStmt:
        """LOAD DATA [LOCAL] INFILE 'p' [REPLACE|IGNORE] INTO TABLE t
        [FIELDS ...] [LINES ...] [IGNORE n LINES] [(cols)]
        (ref: parser.y LoadDataStmt; executor/write.go:1373)."""
        self.expect_word("LOAD")
        self.expect_word("DATA")
        stmt = ast.LoadDataStmt()
        stmt.local = self.try_word("LOCAL")
        self.expect_word("INFILE")
        stmt.path = self._str_lit()
        if self.try_kw("REPLACE"):
            stmt.dup_mode = "replace"
        elif self.try_kw("IGNORE"):
            stmt.dup_mode = "ignore"
        elif stmt.local:
            stmt.dup_mode = "ignore"   # MySQL: LOCAL implies IGNORE
        self.expect_kw("INTO")
        self.expect_kw("TABLE")
        stmt.table = self.table_name()
        if self.try_kw("FIELDS", "COLUMNS"):
            while True:
                if self.try_word("TERMINATED"):
                    self.expect_kw("BY")
                    stmt.fields_terminated = self._str_lit()
                elif self.try_word("OPTIONALLY"):
                    self.expect_word("ENCLOSED")
                    self.expect_kw("BY")
                    stmt.fields_enclosed = self._str_lit()
                elif self.try_word("ENCLOSED"):
                    self.expect_kw("BY")
                    stmt.fields_enclosed = self._str_lit()
                elif self.try_word("ESCAPED"):
                    self.expect_kw("BY")
                    stmt.fields_escaped = self._str_lit()
                else:
                    break
        if self.try_word("LINES"):
            while True:
                if self.try_word("STARTING"):
                    self.expect_kw("BY")
                    stmt.lines_starting = self._str_lit()
                elif self.try_word("TERMINATED"):
                    self.expect_kw("BY")
                    stmt.lines_terminated = self._str_lit()
                else:
                    break
        if self.try_kw("IGNORE"):
            tok = self.next()
            if tok.tp != TokenType.INT:
                raise ParseError("IGNORE requires a row count", tok)
            stmt.ignore_lines = int(tok.val)
            self.expect_word("LINES")
        if self.try_op("("):
            while True:
                stmt.columns.append(self.ident())
                if not self.try_op(","):
                    break
            self.expect_op(")")
        return stmt

    def kill_stmt(self) -> ast.KillStmt:
        """KILL [TIDB] [CONNECTION | QUERY] <id>."""
        self.expect_word("KILL")
        self.try_word("TIDB")
        query_only = False
        if self.try_word("QUERY"):
            query_only = True
        else:
            self.try_word("CONNECTION")
        tok = self.next()
        if tok.tp != TokenType.INT:
            raise ParseError("KILL requires a connection id", tok)
        return ast.KillStmt(conn_id=int(tok.val), query_only=query_only)

    def trace_stmt(self) -> ast.TraceStmt:
        """TRACE [FORMAT = 'row'|'json'] <stmt>."""
        self.expect_word("TRACE")
        fmt = "row"
        if self.try_word("FORMAT"):
            self.expect_op("=")
            tok = self.next()
            if tok.tp != TokenType.STRING:
                raise ParseError(
                    "TRACE FORMAT takes a string literal", tok)
            fmt = tok.val.lower()
            if fmt not in ("row", "json"):
                raise ParseError(
                    f"unsupported TRACE FORMAT {tok.val!r} "
                    f"(use 'row' or 'json')", tok)
        return ast.TraceStmt(stmt=self.statement(), format=fmt)

    def split_table(self) -> ast.SplitTableStmt:
        """SPLIT TABLE t AT (v)[,(v)...] | SPLIT TABLE t REGIONS n."""
        self.expect_word("SPLIT")
        self.expect_kw("TABLE")
        stmt = ast.SplitTableStmt(table=self.table_name())
        if self.try_word("AT"):
            while True:
                self.expect_op("(")
                stmt.at_values.append(self.expr())
                self.expect_op(")")
                if not self.try_op(","):
                    break
        else:
            self.expect_word("REGIONS")
            tok = self.next()
            if tok.tp != TokenType.INT:
                raise ParseError("REGIONS requires a count", tok)
            stmt.regions = int(tok.val)
        return stmt

    # -- SELECT --------------------------------------------------------------

    def select_or_union(self) -> ast.StmtNode:
        first = self.select_core()
        if not (self.peek().is_kw("UNION")):
            return first
        selects = [first]
        alls = []
        while self.try_kw("UNION"):
            is_all = self.try_kw("ALL")
            self.try_kw("DISTINCT") or self.try_word("DISTINCTROW")
            alls.append(is_all)
            selects.append(self.select_core())
        u = ast.UnionStmt(selects=selects, alls=alls)
        if self.try_kw("ORDER"):
            self.expect_kw("BY")
            u.order_by = self.by_list()
        if self.try_kw("LIMIT"):
            u.limit, u.offset = self.limit_clause()
        # MySQL: a trailing ORDER BY / LIMIT binds to the WHOLE union, not
        # the final branch (select_core consumed it while parsing the
        # last SELECT) — hoist it up when the union carries none
        last = selects[-1]
        if not u.order_by and u.limit is None and \
                isinstance(last, ast.SelectStmt) and \
                not getattr(last, "_parenthesized", False) and \
                (last.order_by or last.limit is not None):
            u.order_by, last.order_by = last.order_by, []
            u.limit, u.offset = last.limit, last.offset
            last.limit, last.offset = None, 0
        return u

    def select_core(self) -> ast.SelectStmt:
        if self.try_op("("):
            s = self.select_or_union()
            self.expect_op(")")
            # parenthesized branches keep their own ORDER BY / LIMIT
            # (select_or_union's union-level hoist must skip them)
            s._parenthesized = True
            return s
        self.expect_kw("SELECT")
        s = ast.SelectStmt()
        s.distinct = self.try_kw("DISTINCT") or \
            self.try_word("DISTINCTROW")
        self.try_kw("ALL")
        s.fields.append(self.select_field())
        while self.try_op(","):
            s.fields.append(self.select_field())
        if self.try_kw("FROM"):
            s.from_clause = self.table_refs()
        if self.try_kw("WHERE"):
            s.where = self.expr()
        if self.try_kw("GROUP"):
            self.expect_kw("BY")
            s.group_by = self.by_list()
        if self.try_kw("HAVING"):
            s.having = self.expr()
        if self.try_kw("ORDER"):
            self.expect_kw("BY")
            s.order_by = self.by_list()
        if self.try_kw("LIMIT"):
            s.limit, s.offset = self.limit_clause()
        if self.try_kw("FOR"):
            self.expect_kw("UPDATE")
            s.for_update = True
        elif self.try_word("LOCK"):
            # LOCK IN SHARE MODE: reads are snapshot-consistent already;
            # accepted as the weaker cousin of FOR UPDATE (no row locks)
            self.expect_kw("IN")
            self.expect_word("SHARE")
            self.expect_word("MODE")
        return s

    def select_field(self) -> ast.SelectField:
        t = self.peek()
        if t.tp == TokenType.OP and t.val == "*":
            self.next()
            return ast.SelectField(expr=ast.Star())
        # t.* / db.t.* forms
        if t.tp == TokenType.IDENT and self.peek(1).val == "." and \
                self.peek(2).val == "*":
            self.next(); self.next(); self.next()
            return ast.SelectField(expr=ast.Star(table=t.val))
        if t.tp == TokenType.IDENT and self.peek(1).val == "." and \
                self.peek(2).tp == TokenType.IDENT and \
                self.peek(3).val == "." and self.peek(4).val == "*":
            self.next()
            tbl = self.peek(1).val
            self.next(); self.next(); self.next(); self.next()
            return ast.SelectField(expr=ast.Star(table=tbl))
        e = self.expr()
        alias = ""
        if self.try_kw("AS"):
            if self.peek().tp == TokenType.STRING:
                alias = self.next().val
            else:
                alias = self.ident()
        elif self.peek().tp == TokenType.IDENT:
            alias = self.ident()
        return ast.SelectField(expr=e, alias=alias)

    def by_list(self) -> list[ast.ByItem]:
        items = [self.by_item()]
        while self.try_op(","):
            items.append(self.by_item())
        return items

    def by_item(self) -> ast.ByItem:
        e = self.expr()
        desc = False
        if self.try_kw("DESC"):
            desc = True
        else:
            self.try_kw("ASC")
        return ast.ByItem(expr=e, desc=desc)

    def limit_clause(self) -> tuple[int, int]:
        a = self._int_lit()
        if self.try_op(","):
            return self._int_lit(), a       # LIMIT offset, count
        if self.try_kw("OFFSET"):
            return a, self._int_lit()
        return a, 0

    def _int_lit(self) -> int:
        t = self.next()
        if t.tp != TokenType.INT:
            raise ParseError("expected integer", t)
        return int(t.val)

    # -- table refs ----------------------------------------------------------

    def table_refs(self):
        left = self.table_ref()
        while True:
            if self.try_op(","):
                right = self.table_ref()
                left = ast.Join(left, right, ast.JoinType.CROSS)
            elif self.peek().is_kw("JOIN") or self.peek().is_kw("INNER") or \
                    self.peek().is_kw("CROSS") or self.peek().is_kw("LEFT") \
                    or self.peek().is_kw("RIGHT"):
                left = self._join_rest(left)
            elif self.peek().tp == TokenType.IDENT and \
                    self.peek().val.upper() == "STRAIGHT_JOIN":
                # optimizer-order hint; join order is the planner's call
                self.next()
                right = self.table_ref()
                j = ast.Join(left, right, ast.JoinType.INNER)
                if self.try_kw("ON"):
                    j.on = self.expr()
                left = j
            elif self.peek().tp == TokenType.IDENT and \
                    self.peek().val.upper() == "NATURAL":
                self.next()
                left = self._join_rest(left)
                left.natural = True     # join columns = common names
            else:
                return left

    def _join_rest(self, left):
        tp = ast.JoinType.INNER
        if self.try_kw("LEFT"):
            tp = ast.JoinType.LEFT
            self.try_kw("OUTER")
        elif self.try_kw("RIGHT"):
            tp = ast.JoinType.RIGHT
            self.try_kw("OUTER")
        elif self.try_kw("CROSS"):
            tp = ast.JoinType.CROSS
        else:
            self.try_kw("INNER")
        self.expect_kw("JOIN")
        right = self.table_ref()
        j = ast.Join(left, right, tp)
        if self.try_kw("ON"):
            j.on = self.expr()
        elif self.try_kw("USING"):
            self.expect_op("(")
            j.using = [self.ident()]
            while self.try_op(","):
                j.using.append(self.ident())
            self.expect_op(")")
        return j

    def table_ref(self):
        if self.try_op("("):
            if self.peek().is_kw("SELECT"):
                sub = self.select_or_union()
                self.expect_op(")")
                alias = ""
                self.try_kw("AS")
                if self.peek().tp == TokenType.IDENT:
                    alias = self.ident()
                return ast.SubqueryTable(select=sub, alias=alias)
            inner = self.table_refs()
            self.expect_op(")")
            return inner
        ts = self.table_name()
        if self.try_kw("AS"):
            ts.alias = self.ident()
        elif self.peek().tp == TokenType.IDENT and \
                self.peek().val.upper() not in ("LOCK", "STRAIGHT_JOIN",
                                                "NATURAL") and \
                not self._at_index_hint():
            ts.alias = self.ident()
        while self._at_index_hint():
            kind = self.next().val.upper()
            self.next()                       # INDEX | KEY
            if self.try_kw("FOR"):            # FOR JOIN|ORDER BY|GROUP BY
                if not self.try_kw("JOIN"):
                    self.try_kw("ORDER") or self.try_kw("GROUP")
                    self.expect_kw("BY")
            self.expect_op("(")
            names = []
            if not (self.peek().tp == TokenType.OP and
                    self.peek().val == ")"):
                names.append(self.ident())
                while self.try_op(","):
                    names.append(self.ident())
            self.expect_op(")")
            ts.index_hints.append((kind, names))
        return ts

    def _at_index_hint(self) -> bool:
        """USE|IGNORE|FORCE INDEX|KEY ( ... ) after a table factor."""
        t, t1 = self.peek(), self.peek(1)
        w = t.val.upper() if t.tp in (TokenType.KEYWORD,
                                      TokenType.IDENT) else ""
        w1 = t1.val.upper() if t1.tp in (TokenType.KEYWORD,
                                         TokenType.IDENT) else ""
        return w in ("USE", "IGNORE", "FORCE") and w1 in ("INDEX", "KEY")

    def table_name(self) -> ast.TableSource:
        a = self.ident()
        if self.try_op("."):
            return ast.TableSource(name=self.ident(), db=a)
        return ast.TableSource(name=a)

    # -- INSERT / UPDATE / DELETE -------------------------------------------

    def insert(self) -> ast.InsertStmt:
        is_replace = self.peek().val == "REPLACE"
        self.next()
        stmt = ast.InsertStmt(is_replace=is_replace)
        stmt.ignore = self.try_kw("IGNORE")
        self.try_kw("INTO")
        stmt.table = self.table_name()
        if self.peek().tp == TokenType.OP and self.peek().val == "(":
            # could be column list or SELECT
            if self.peek(1).is_kw("SELECT"):
                self.next()
                stmt.select = self.select_or_union()
                self.expect_op(")")
                return stmt
            self.expect_op("(")
            if not self.try_op(")"):       # () = explicit empty list
                stmt.columns.append(self.ident())
                while self.try_op(","):
                    stmt.columns.append(self.ident())
                self.expect_op(")")
        if self.try_kw("VALUES") or self.try_kw("VALUE"):
            stmt.values.append(self.value_row())
            while self.try_op(","):
                stmt.values.append(self.value_row())
        elif self.peek().is_kw("SELECT"):
            stmt.select = self.select_or_union()
        elif self.try_kw("SET"):
            row = []
            while True:
                c = self.column_name()
                self.expect_op("=")
                stmt.columns.append(c.name)
                row.append(self.expr_or_default())
                if not self.try_op(","):
                    break
            stmt.values = [row]
        else:
            raise ParseError("expected VALUES or SELECT", self.peek())
        if self.try_kw("ON"):
            self.expect_kw("DUPLICATE")
            self.expect_kw("KEY")
            self.expect_kw("UPDATE")
            stmt.on_duplicate.append(self.assignment())
            while self.try_op(","):
                stmt.on_duplicate.append(self.assignment())
        return stmt

    def value_row(self) -> list:
        self.expect_op("(")
        if self.try_op(")"):
            return []
        row = [self.expr_or_default()]
        while self.try_op(","):
            row.append(self.expr_or_default())
        self.expect_op(")")
        return row

    def expr_or_default(self):
        nt = self.peek(1)
        if self.peek().is_kw("DEFAULT") and not (
                nt.tp == TokenType.OP and nt.val == "("):
            self.next()
            return ast.DefaultExpr()
        return self.expr()

    def assignment(self) -> ast.Assignment:
        c = self.column_name()
        self.expect_op("=")
        return ast.Assignment(col=c, expr=self.expr_or_default())

    def update(self) -> ast.UpdateStmt:
        self.expect_kw("UPDATE")
        stmt = ast.UpdateStmt()
        stmt.table = self.table_refs()
        self.expect_kw("SET")
        stmt.assignments.append(self.assignment())
        while self.try_op(","):
            stmt.assignments.append(self.assignment())
        if self.try_kw("WHERE"):
            stmt.where = self.expr()
        if self.try_kw("ORDER"):
            self.expect_kw("BY")
            stmt.order_by = self.by_list()
        if self.try_kw("LIMIT"):
            stmt.limit, _ = self.limit_clause()
        return stmt

    def delete(self) -> ast.DeleteStmt:
        self.expect_kw("DELETE")
        if not self.peek().is_kw("FROM"):
            # DELETE t1, t2 FROM <refs> ...
            targets = [self.table_name()]
            while self.try_op(","):
                targets.append(self.table_name())
            self.expect_kw("FROM")
            refs = self.table_refs()
            stmt = ast.DeleteStmt(targets=targets, refs=refs)
            if self.try_kw("WHERE"):
                stmt.where = self.expr()
            return stmt
        self.expect_kw("FROM")
        first = self.table_name()
        if self.try_op(",") or self.peek_word() == "USING":
            # DELETE FROM t1[, t2] USING <refs> ...
            targets = [first]
            while self.peek().tp == TokenType.IDENT:
                targets.append(self.table_name())
                if not self.try_op(","):
                    break
            self.expect_word("USING")
            refs = self.table_refs()
            stmt = ast.DeleteStmt(targets=targets, refs=refs)
            if self.try_kw("WHERE"):
                stmt.where = self.expr()
            return stmt
        stmt = ast.DeleteStmt(table=first)
        if self.try_kw("WHERE"):
            stmt.where = self.expr()
        if self.try_kw("ORDER"):
            self.expect_kw("BY")
            stmt.order_by = self.by_list()
        if self.try_kw("LIMIT"):
            stmt.limit, _ = self.limit_clause()
        return stmt

    # -- DDL -----------------------------------------------------------------

    def create(self) -> ast.StmtNode:
        self.expect_kw("CREATE")
        if self.try_kw("USER"):
            ine = self._if_not_exists()
            users = [self._user_spec(with_password=True)]
            while self.try_op(","):
                users.append(self._user_spec(with_password=True))
            return ast.CreateUserStmt(users=users, if_not_exists=ine)
        if self.try_kw("DATABASE") or self.try_kw("SCHEMA"):
            ine = self._if_not_exists()
            return ast.CreateDatabaseStmt(name=self.ident(),
                                          if_not_exists=ine)
        # CREATE [OR REPLACE] [ALGORITHM=...] [DEFINER=...]
        # [SQL SECURITY ...] VIEW v [(cols)] AS select ... — parsed to
        # the AST like the reference (ast/ddl.go CreateViewStmt), and
        # like the reference's planner, EXECUTION rejects it loudly
        # (views are unimplemented there too)
        save = self.i
        or_replace = False
        if self.try_kw("OR"):
            if not self.try_word("REPLACE") and not self.try_kw("REPLACE"):
                self.i = save
            else:
                or_replace = True
        while self.peek_word() in ("ALGORITHM", "DEFINER", "SQL"):
            w = self.next().val.upper()
            if w == "SQL":
                self.expect_word("SECURITY")
                self.next()                 # DEFINER | INVOKER
            else:
                self.try_op("=")
                self.next()                 # undefined/merge/'root'/...
        if self.try_word("VIEW"):
            name = self.table_name()
            cols = []
            if self.peek().tp == TokenType.OP and self.peek().val == "(":
                cols = self._paren_idents()
            self.expect_kw("AS")
            sel = self.select_or_union()
            if self.try_kw("WITH"):
                self.try_word("LOCAL") or self.try_word("CASCADED")
                self.expect_kw("CHECK")
                self.expect_word("OPTION")
            return ast.CreateViewStmt(view=name, columns=cols,
                                      select=sel, or_replace=or_replace)
        if or_replace or self.i != save:
            raise ParseError("expected VIEW", self.peek())
        unique = self.try_kw("UNIQUE")
        if self.try_kw("INDEX"):
            name = self.ident()
            self._index_using()            # CREATE INDEX i USING BTREE ON ...
            self.expect_kw("ON")
            table = self.table_name()
            # _paren_idents accepts prefix lengths col(10) and ASC/DESC
            # (prefix indexing stores the full value — DEVIATIONS.md)
            cols = self._paren_idents()
            # trailing index options: USING, COMMENT (accepted, fixed
            # implementation — there is one index layout)
            while True:
                if self._index_using():
                    continue
                if self.try_kw("COMMENT"):
                    self.next()
                    continue
                break
            return ast.CreateIndexStmt(index_name=name, table=table,
                                       columns=cols, unique=unique)
        if unique:
            raise ParseError("expected INDEX after UNIQUE", self.peek())
        self.try_kw("TEMPORARY")
        self.expect_kw("TABLE")
        ine = self._if_not_exists()
        stmt = ast.CreateTableStmt(table=self.table_name(),
                                   if_not_exists=ine)
        if self.try_kw("LIKE"):
            stmt.like_table = self.table_name()
            return stmt
        if self.peek().tp == TokenType.OP and self.peek().val == "(" \
                and self.peek(1).tp == TokenType.KEYWORD and \
                self.peek(1).val == "LIKE":
            self.next()
            self.next()
            stmt.like_table = self.table_name()
            self.expect_op(")")
            return stmt
        self.expect_op("(")
        while True:
            if self.try_kw("PRIMARY"):
                self.expect_kw("KEY")
                if self.peek().tp == TokenType.IDENT:
                    self.ident()     # optional constraint name, ignored
                stmt.indexes.append(ast.IndexDef(
                    name="PRIMARY", columns=self._paren_idents(),
                    unique=True, primary=True))
            elif self.try_kw("UNIQUE"):
                self.try_kw("KEY") or self.try_kw("INDEX")
                name = "" if self.peek().val == "(" else self.ident()
                stmt.indexes.append(ast.IndexDef(
                    name=name, columns=self._paren_idents(), unique=True))
                self._index_tail_options()
            elif self.try_kw("KEY") or self.try_kw("INDEX"):
                name = "" if self.peek().val == "(" else self.ident()
                stmt.indexes.append(ast.IndexDef(
                    name=name, columns=self._paren_idents()))
                self._index_tail_options()
            elif self.try_kw("CHECK"):
                # table-level CHECK constraint: parsed + IGNORED (as
                # MySQL did before 8.0.16)
                self.expect_op("(")
                depth = 1
                while depth:
                    tk = self.next()
                    if tk.tp == TokenType.OP and tk.val == "(":
                        depth += 1
                    elif tk.tp == TokenType.OP and tk.val == ")":
                        depth -= 1
                    elif tk.tp == TokenType.EOF:
                        raise ParseError("unterminated CHECK", tk)
            elif self.peek_word() == "FULLTEXT":
                # fulltext layout: stored as a plain secondary index
                # (MATCH() search is unsupported — DEVIATIONS.md)
                self.next()
                self.try_kw("KEY") or self.try_kw("INDEX")
                name = "" if self.peek().val == "(" else self.ident()
                stmt.indexes.append(ast.IndexDef(
                    name=name, columns=self._paren_idents()))
                self._index_tail_options()
            elif self.try_kw("CONSTRAINT"):
                # CONSTRAINT [name] UNIQUE/PRIMARY/FOREIGN KEY ...
                if self.peek().tp == TokenType.IDENT:
                    self.ident()
                continue
            elif self.try_kw("FOREIGN"):
                self.expect_kw("KEY")
                self._paren_idents()
                self.expect_kw("REFERENCES")
                self.table_name()
                self._paren_idents()
                # FK constraints parsed + ignored (reference also defers FKs)
            else:
                stmt.columns.append(self.column_def())
            if not self.try_op(","):
                break
        self.expect_op(")")
        # table options (ref: parser.y TableOption — the storage-engine
        # tuning knobs are accepted and recorded, not acted on)
        _OPTS = ("ENGINE", "CHARSET", "COLLATE", "COMMENT",
                 "AUTO_INCREMENT", "ROW_FORMAT", "KEY_BLOCK_SIZE",
                 "CHECKSUM", "DELAY_KEY_WRITE", "MAX_ROWS", "MIN_ROWS",
                 "AVG_ROW_LENGTH", "CONNECTION", "PASSWORD",
                 "STATS_PERSISTENT", "COMPRESSION")
        while True:
            self.try_op(",")       # options may be comma-separated
            t = self.peek()
            name = t.val.upper() if t.tp in (TokenType.KEYWORD,
                                             TokenType.IDENT) else ""
            if name == "DEFAULT":
                self.next()
                name = self.peek().val.upper()
                if name == "CHARACTER":
                    self.next()
                    self.expect_kw("SET")
                    self.try_op("=")
                    stmt.options["charset"] = self.next().val
                    continue
                if name in ("CHARSET", "COLLATE"):
                    opt = self.next().val
                    self.try_op("=")
                    stmt.options[opt.lower()] = self.next().val
                    continue
                raise ParseError("expected CHARSET/COLLATE", self.peek())
            if name == "CHARACTER":
                self.next()
                self.expect_kw("SET")
                self.try_op("=")
                stmt.options["charset"] = self.next().val
                continue
            if name in _OPTS:
                self.next()
                self.try_op("=")
                stmt.options[name.lower()] = self.next().val
                continue
            if name == "PARTITION" and self.peek_word(1) == "BY":
                # partitioning clause: parsed + IGNORED (regions already
                # range-partition storage; DEVIATIONS.md)
                depth = 0
                while True:
                    t2 = self.peek()
                    if t2.tp == TokenType.EOF:
                        break
                    if t2.tp == TokenType.OP and t2.val == "(":
                        depth += 1
                    elif t2.tp == TokenType.OP and t2.val == ")":
                        depth -= 1
                    elif t2.tp == TokenType.OP and t2.val == ";" and \
                            depth == 0:
                        break
                    self.next()
                continue
            break
        return stmt

    def _index_tail_options(self) -> None:
        """Inline index definitions accept [USING ...] [COMMENT '...']."""
        while True:
            if self._index_using():
                continue
            if self.try_kw("COMMENT"):
                self.next()
                continue
            break

    def _index_using(self) -> bool:
        """[USING BTREE|HASH] — accepted; one index layout exists."""
        if self.try_kw("USING"):
            t = self.next()
            if t.val.upper() not in ("BTREE", "HASH"):
                raise ParseError("expected BTREE or HASH", t)
            return True
        return False

    def _if_not_exists(self) -> bool:
        if self.try_kw("IF"):
            self.expect_kw("NOT")
            self.expect_kw("EXISTS")
            return True
        return False

    def _paren_idents(self) -> list[str]:
        self.expect_op("(")
        out = [self.ident()]
        # ignore optional key length e.g. col(10) and ASC/DESC order
        if self.try_op("("):
            self._int_lit()
            self.expect_op(")")
        self.try_kw("ASC") or self.try_kw("DESC")
        while self.try_op(","):
            out.append(self.ident())
            if self.try_op("("):
                self._int_lit()
                self.expect_op(")")
            self.try_kw("ASC") or self.try_kw("DESC")
        self.expect_op(")")
        return out

    def column_def(self) -> ast.ColumnDef:
        name = self.ident()
        ft = self.field_type()
        d = ast.ColumnDef(name=name, ft=ft)
        if getattr(self, "_last_type_collation", None) is not None:
            d.explicit_collation = True
        flags = ft.flags
        while True:
            if self.try_kw("NOT"):
                self.expect_kw("NULL")
                flags |= st.Flag.NOT_NULL
            elif self.try_kw("NULL"):
                pass
            elif self.try_kw("DEFAULT"):
                d.default = self.expr_or_null_literal()
                d.has_default = True
            elif self.try_kw("AUTO_INCREMENT"):
                d.auto_increment = True
                flags |= st.Flag.AUTO_INCREMENT
            elif self.try_kw("PRIMARY"):
                self.expect_kw("KEY")
                d.is_primary = True
                flags |= st.Flag.PRI_KEY | st.Flag.NOT_NULL
            elif self.try_kw("UNIQUE"):
                self.try_kw("KEY")
                d.is_unique = True
                flags |= st.Flag.UNIQUE_KEY
            elif self.try_kw("KEY"):
                pass
            elif self.try_kw("COMMENT"):
                d.comment = self.next().val
            elif self.try_kw("COLLATE"):
                coll = self.next().val.lower()
                if ft.eval_type == st.EvalType.STRING:
                    import dataclasses
                    ft = dataclasses.replace(ft, collation=coll)
                    d.ft = ft
                    d.explicit_collation = True
            elif self.try_kw("CHARSET"):
                self.next()
            elif self.peek_word() == "CHARACTER" and \
                    self.peek_word(1) == "SET":
                self.next()
                self.next()
                self.next()
            elif self.try_kw("ON"):
                # ON UPDATE CURRENT_TIMESTAMP[(n)]: parsed + ignored
                # (auto-update timestamps — DEVIATIONS.md)
                self.expect_kw("UPDATE")
                self.next()
                if self.try_op("("):
                    if self.peek().tp == TokenType.INT:
                        self.next()
                    self.expect_op(")")
            elif self.try_kw("CHECK"):
                # inline CHECK constraints: parsed + IGNORED, as MySQL
                # did before 8.0.16
                self.expect_op("(")
                depth = 1
                while depth:
                    tk = self.next()
                    if tk.tp == TokenType.OP and tk.val == "(":
                        depth += 1
                    elif tk.tp == TokenType.OP and tk.val == ")":
                        depth -= 1
                    elif tk.tp == TokenType.EOF:
                        raise ParseError("unterminated CHECK", tk)
            elif self.try_kw("REFERENCES"):
                # inline column REFERENCES (incl. MATCH / ON DELETE /
                # ON UPDATE): parsed and IGNORED, exactly as MySQL does
                # (only table-level FOREIGN KEY creates the constraint)
                self.table_name()
                if self.peek().tp == TokenType.OP and \
                        self.peek().val == "(":
                    self._paren_idents()
                while True:
                    if self.peek().tp == TokenType.IDENT and \
                            self.peek().val.upper() == "MATCH":
                        self.next()
                        self.ident()
                    elif self.try_kw("ON"):
                        if not (self.try_kw("DELETE") or
                                self.try_kw("UPDATE")):
                            raise ParseError("expected DELETE or UPDATE",
                                             self.peek())
                        if not (self.try_kw("SET") and
                                self.try_kw("NULL")):
                            if self.peek().val.upper() in (
                                    "CASCADE", "RESTRICT"):
                                self.next()
                            elif self.try_kw("NOT"):
                                self.ident()   # NO ACTION spelled oddly
                            else:
                                self.ident()   # NO / ACTION words
                                if self.peek().val.upper() == "ACTION":
                                    self.next()
                    else:
                        break
            else:
                break
        d.ft = ft.with_flags(flags)
        return d

    def expr_or_null_literal(self):
        if self.try_kw("NULL"):
            return ast.Literal(None)
        return self.expr()

    def field_type(self) -> st.FieldType:
        t = self.next()
        # ENUM is deliberately NOT a reserved word (matching MySQL);
        # type names arrive as IDENT or KEYWORD alike
        if t.tp not in (TokenType.KEYWORD, TokenType.IDENT):
            raise ParseError("expected type", t)
        name = t.val.upper()
        if name == "NATIONAL":
            t = self.next()
            name = t.val.upper()          # national char/varchar
        _SYNONYMS = {"INT1": "TINYINT", "INT2": "SMALLINT",
                     "INT3": "MEDIUMINT", "INT4": "INT",
                     "INT8": "BIGINT", "MIDDLEINT": "MEDIUMINT",
                     "DEC": "DECIMAL", "FIXED": "DECIMAL",
                     "NCHAR": "CHAR", "NVARCHAR": "VARCHAR",
                     "SERIAL": "BIGINT"}
        name = _SYNONYMS.get(name, name)
        if name in ("ENUM", "SET"):
            # ENUM('a','b',...) / SET('a','b',...)
            self.expect_op("(")
            elems = [self._str_lit()]
            while self.try_op(","):
                elems.append(self._str_lit())
            self.expect_op(")")
            TC = st.TypeCode
            return st.FieldType(TC.ENUM if name == "ENUM" else TC.SET,
                                elems=tuple(elems))
        # two-word type names are consumed up front, before length/flags
        if name == "DOUBLE":
            self.try_kw("PRECISION")
        if name == "CHAR":
            self.try_kw("VARYING")
        flen, frac = -1, -1
        if self.try_op("("):
            flen = self._int_lit()
            if self.try_op(","):
                frac = self._int_lit()
            self.expect_op(")")
        flags = 0
        collation = None
        while True:
            if self.try_kw("UNSIGNED"):
                flags |= st.Flag.UNSIGNED
            elif self.try_kw("SIGNED") or self.try_kw("ZEROFILL"):
                pass
            elif self.try_word("BINARY"):
                pass   # binary attribute == the default _bin collation
            elif self.peek_word() == "CHARACTER" and \
                    self.peek_word(1) == "SET":
                self.next()
                self.next()
                self.next()               # charset name: accepted, fixed
            elif self.try_kw("CHARSET"):
                self.next()
            elif self.try_kw("COLLATE"):
                collation = self.next().val.lower()
            else:
                break
        TC = st.TypeCode
        mapping = {
            "INT": TC.LONG, "INTEGER": TC.LONG, "BIGINT": TC.LONGLONG,
            "SMALLINT": TC.SHORT, "TINYINT": TC.TINY, "MEDIUMINT": TC.INT24,
            "BOOL": TC.TINY, "BOOLEAN": TC.TINY,
            "FLOAT": TC.FLOAT, "DOUBLE": TC.DOUBLE, "REAL": TC.DOUBLE,
            "DECIMAL": TC.NEWDECIMAL, "NUMERIC": TC.NEWDECIMAL,
            "CHAR": TC.STRING, "VARCHAR": TC.VARCHAR, "TEXT": TC.BLOB,
            "BLOB": TC.BLOB, "BINARY": TC.STRING, "VARBINARY": TC.VARCHAR,
            "TINYTEXT": TC.BLOB, "MEDIUMTEXT": TC.BLOB,
            "LONGTEXT": TC.BLOB, "TINYBLOB": TC.BLOB,
            "MEDIUMBLOB": TC.BLOB, "LONGBLOB": TC.BLOB,
            "BIT": TC.TINY,
            "DATE": TC.DATE, "DATETIME": TC.DATETIME,
            "TIMESTAMP": TC.TIMESTAMP, "TIME": TC.DURATION,
            "YEAR": TC.YEAR, "JSON": TC.JSON,
        }
        if name not in mapping:
            raise ParseError(f"unsupported type {name}", t)
        tp = mapping[name]
        if tp == TC.NEWDECIMAL:
            if flen < 0:
                flen = 10
            if frac < 0:
                frac = 0
        ft = st.FieldType(tp, flags=flags, flen=flen, frac=frac)
        if collation is not None and \
                ft.eval_type == st.EvalType.STRING:
            import dataclasses
            ft = dataclasses.replace(ft, collation=collation)
        # column_def checks this to mark an explicit column collation
        self._last_type_collation = collation
        return ft

    # -- account management (ref: parser.y GrantStmt/CreateUserStmt) --------

    def _user_spec(self, with_password: bool = False) -> ast.UserSpec:
        """'name'[@'host'] [IDENTIFIED BY 'pw'] — name/host accept quoted
        strings or bare identifiers."""
        t = self.peek()
        if t.tp == TokenType.STRING:
            self.next()
            name = t.val
        else:
            name = self.ident()
        host = "%"
        if self.try_op("@"):
            t = self.peek()
            if t.tp == TokenType.STRING:
                self.next()
                host = t.val
            else:
                host = self.ident()
        spec = ast.UserSpec(user=name, host=host)
        if with_password and self.try_kw("IDENTIFIED"):
            self.expect_kw("BY")
            t = self.next()
            if t.tp != TokenType.STRING:
                raise ParseError("IDENTIFIED BY takes a string literal", t)
            spec.password = t.val
        return spec

    _PRIV_NAMES = {"SELECT", "INSERT", "UPDATE", "DELETE", "CREATE", "DROP",
                   "ALTER", "INDEX", "SUPER"}

    def grant_revoke(self, is_grant: bool) -> ast.StmtNode:
        self.next()          # GRANT / REVOKE
        privs = []
        if self.try_kw("ALL"):
            self.try_kw("PRIVILEGES")
            privs.append("ALL")
        else:
            while True:
                t = self.next()
                name = t.val.upper()
                if name == "CREATE" and self.peek_word() == "USER":
                    self.next()
                    name = "CREATE USER"
                elif name == "GRANT" and self.peek_word() == "OPTION":
                    self.next()
                    name = "GRANT"
                elif name not in self._PRIV_NAMES:
                    raise ParseError(f"unknown privilege {t.val!r}", t)
                privs.append(name)
                if not self.try_op(","):
                    break
        self.expect_kw("ON")
        # *.* (global) | * (current db) | db.* | db.tbl | tbl
        if self.try_op("*"):
            if self.try_op("."):
                self.expect_op("*")
                db = tbl = "*"           # *.*: global scope
            else:
                db, tbl = "", "*"        # bare *: current database (MySQL)
        else:
            first = self.ident()
            if self.try_op("."):
                db = first
                if self.try_op("*"):
                    tbl = "*"
                else:
                    tbl = self.ident()
            else:
                db, tbl = "", first      # current db at execution time
        self.expect_kw("TO" if is_grant else "FROM")
        users = [self._user_spec()]
        while self.try_op(","):
            users.append(self._user_spec())
        if is_grant and self.try_kw("WITH"):
            # WITH GRANT OPTION == granting the GRANT privilege bit
            self.expect_kw("GRANT")
            self.expect_kw("OPTION")
            privs.append("GRANT")
        cls = ast.GrantStmt if is_grant else ast.RevokeStmt
        return cls(privs=privs, db=db, table=tbl, users=users)

    def drop(self) -> ast.StmtNode:
        self.expect_kw("DROP")
        if self.try_kw("USER"):
            ie = self._if_exists()
            users = [self._user_spec()]
            while self.try_op(","):
                users.append(self._user_spec())
            return ast.DropUserStmt(users=users, if_exists=ie)
        if self.try_kw("DATABASE") or self.try_kw("SCHEMA"):
            ie = self._if_exists()
            return ast.DropDatabaseStmt(name=self.ident(), if_exists=ie)
        if self.try_kw("INDEX"):
            name = self.ident()
            self.expect_kw("ON")
            return ast.DropIndexStmt(index_name=name,
                                     table=self.table_name())
        if self.try_word("VIEW"):
            # views don't exist here: DROP VIEW IF EXISTS is the common
            # migration-script form — accept it as a no-op; plain DROP
            # VIEW on a missing view errors like MySQL
            ie = self._if_exists()
            tables = [self.table_name()]
            while self.try_op(","):
                tables.append(self.table_name())
            return ast.DropViewStmt(tables=tables, if_exists=ie)
        if self.try_word("STATS"):
            return ast.DropStatsStmt(table=self.table_name())
        if not (self.try_kw("TABLE") or self.try_word("TABLES")):
            raise ParseError("expected TABLE", self.peek())
        ie = self._if_exists()
        tables = [self.table_name()]
        while self.try_op(","):
            tables.append(self.table_name())
        return ast.DropTableStmt(tables=tables, if_exists=ie)

    def _if_exists(self) -> bool:
        if self.try_kw("IF"):
            self.expect_kw("EXISTS")
            return True
        return False

    def alter(self) -> ast.AlterTableStmt:
        self.expect_kw("ALTER")
        self.expect_kw("TABLE")
        stmt = ast.AlterTableStmt(table=self.table_name())
        while True:
            stmt.specs.append(self.alter_spec())
            if not self.try_op(","):
                break
        return stmt

    def alter_spec(self) -> ast.AlterSpec:
        if self.try_kw("ADD"):
            self.try_word("FULLTEXT")   # fulltext layout: plain index here
            if self.try_kw("INDEX") or self.try_kw("KEY"):
                name = "" if self.peek().val == "(" else self.ident()
                spec = ast.AlterSpec(tp="add_index", index=ast.IndexDef(
                    name=name, columns=self._paren_idents()))
                self._index_tail_options()
                return spec
            if self.try_kw("UNIQUE"):
                self.try_kw("INDEX") or self.try_kw("KEY")
                name = "" if self.peek().val == "(" else self.ident()
                spec = ast.AlterSpec(tp="add_index", index=ast.IndexDef(
                    name=name, columns=self._paren_idents(), unique=True))
                self._index_tail_options()
                return spec
            if self.try_kw("PRIMARY"):
                self.expect_kw("KEY")
                spec = ast.AlterSpec(tp="add_index", index=ast.IndexDef(
                    name="PRIMARY", columns=self._paren_idents(),
                    unique=True, primary=True))
                self._index_tail_options()
                return spec
            self.try_kw("COLUMN")
            if self.peek().tp == TokenType.OP and self.peek().val == "(":
                # ADD COLUMN (a INT, b VARCHAR(10)): multi-column form
                self.next()
                cols = [self.column_def()]
                while self.try_op(","):
                    cols.append(self.column_def())
                self.expect_op(")")
                return ast.AlterSpec(tp="add_columns", columns=cols)
            spec = ast.AlterSpec(tp="add_column", column=self.column_def())
            if self.try_kw("FIRST"):
                spec.position = "first"
            elif self.try_kw("AFTER"):
                spec.position = "after"
                spec.after_col = self.ident()
            return spec
        if self.try_kw("DROP"):
            if self.try_kw("INDEX") or self.try_kw("KEY"):
                return ast.AlterSpec(tp="drop_index", name=self.ident())
            if self.try_kw("PRIMARY"):
                self.expect_kw("KEY")
                return ast.AlterSpec(tp="drop_index", name="PRIMARY")
            self.try_kw("COLUMN")
            return ast.AlterSpec(tp="drop_column", name=self.ident())
        if self.try_kw("MODIFY"):
            self.try_kw("COLUMN")
            return ast.AlterSpec(tp="modify_column", column=self.column_def())
        if self.try_kw("CHANGE"):
            self.try_kw("COLUMN")
            old = self.ident()
            spec = ast.AlterSpec(tp="change_column",
                                 column=self.column_def())
            spec.name = old
            if self.try_kw("FIRST"):
                spec.position = "first"
            elif self.try_kw("AFTER"):
                spec.position = "after"
                spec.after_col = self.ident()
            return spec
        if self.try_kw("ALTER"):
            # ALTER [COLUMN] a SET DEFAULT v | DROP DEFAULT
            self.try_kw("COLUMN")
            col = self.ident()
            if self.try_kw("SET"):
                self.expect_kw("DEFAULT")
                return ast.AlterSpec(tp="set_default", name=col,
                                     default=self.expr())
            self.expect_kw("DROP")
            self.expect_kw("DEFAULT")
            return ast.AlterSpec(tp="drop_default", name=col)
        if self.try_kw("RENAME"):
            self.try_kw("TO") or self.try_kw("AS")
            tn = self.table_name()
            return ast.AlterSpec(tp="rename", name=tn.name,
                                 new_db=tn.db)
        if self.try_word("DISABLE") or self.try_word("ENABLE"):
            # DISABLE/ENABLE KEYS: MyISAM bulk-load hint, no-op here
            self.expect_word("KEYS")
            return ast.AlterSpec(tp="noop")
        word = self.peek_word()
        if word in ("LOCK", "ALGORITHM"):
            # online-DDL hints: LOCK=NONE|DEFAULT|SHARED|EXCLUSIVE,
            # ALGORITHM=INPLACE|COPY|DEFAULT — accepted; this DDL is
            # always online (F1 states), so the hints are no-ops
            self.next()
            self.try_op("=")
            self.next()
            return ast.AlterSpec(tp="noop")
        if word == "DEFAULT" and self.peek_word(1) in (
                "COLLATE", "CHARSET", "CHARACTER"):
            self.next()
            word = self.peek_word()
        if word in ("ENGINE", "COMMENT", "COLLATE", "CHARSET",
                    "ROW_FORMAT", "KEY_BLOCK_SIZE", "CHECKSUM",
                    "AUTO_INCREMENT", "DELAY_KEY_WRITE"):
            # ALTER-time table options: accepted + ignored (no storage
            # engines / formats to switch)
            self.next()
            self.try_op("=")
            self.next()
            return ast.AlterSpec(tp="noop")
        if word == "CHARACTER" and self.peek_word(1) == "SET":
            self.next()
            self.next()
            self.try_op("=")
            self.next()
            return ast.AlterSpec(tp="noop")
        raise ParseError("unsupported ALTER spec", self.peek())

    def rename(self) -> ast.RenameTableStmt:
        self.expect_kw("RENAME")
        self.expect_kw("TABLE")
        pairs = []
        while True:
            old = self.table_name()
            self.expect_kw("TO")
            pairs.append((old, self.table_name()))
            if not self.try_op(","):
                break
        return ast.RenameTableStmt(pairs=pairs)

    # -- SET / SHOW ----------------------------------------------------------

    def set_stmt(self) -> ast.SetStmt:
        self.expect_kw("SET")
        stmt = ast.SetStmt()
        # client-preamble forms: SET NAMES cs [COLLATE c] / SET CHARACTER
        # SET cs — recorded as plain session sysvars
        if self.peek().tp == TokenType.IDENT and \
                self.peek().val.upper() == "NAMES":
            self.next()
            cs = self.ident() if self.peek().tp != TokenType.STRING \
                else self.next().val
            if self.try_kw("COLLATE"):
                self.ident()
            for n in ("character_set_client", "character_set_results",
                      "character_set_connection"):
                stmt.assignments.append(ast.VarAssignment(
                    name=n, is_system=True, value=ast.Literal(cs)))
            return stmt
        if self.peek().tp in (TokenType.IDENT, TokenType.KEYWORD) and \
                self.peek().val.upper() == "CHARACTER":
            self.next()
            self.expect_kw("SET")
            cs = self.ident() if self.peek().tp != TokenType.STRING \
                else self.next().val
            stmt.assignments.append(ast.VarAssignment(
                name="character_set_client", is_system=True,
                value=ast.Literal(cs)))
            return stmt
        if self.peek().val.upper() == "PASSWORD" and \
                self.peek().tp in (TokenType.IDENT, TokenType.KEYWORD):
            # SET PASSWORD [FOR user] = 'pw'
            self.next()
            user = None
            if self.try_kw("FOR"):
                user = self._user_spec()
            self.expect_op("=")
            t = self.next()
            if t.tp != TokenType.STRING:
                raise ParseError("SET PASSWORD takes a string", t)
            return ast.SetPasswordStmt(user=user, password=t.val)
        if self.peek().val.upper() == "TRANSACTION" or (
                self.peek().val.upper() in ("SESSION", "GLOBAL", "LOCAL")
                and self.peek(1).val.upper() == "TRANSACTION"):
            # SET [SESSION|GLOBAL] TRANSACTION ISOLATION LEVEL ... /
            # READ ONLY|WRITE — mapped onto the isolation sysvars
            is_global = False
            if self.peek().val.upper() in ("SESSION", "GLOBAL", "LOCAL"):
                is_global = self.next().val.upper() == "GLOBAL"
            self.next()                    # TRANSACTION
            if self.try_word("READ"):
                t = self.next()            # ONLY | WRITE
                if t.val.upper() not in ("ONLY", "WRITE"):
                    raise ParseError("expected ONLY or WRITE", t)
                stmt.assignments.append(ast.VarAssignment(
                    name="transaction_read_only", is_system=True,
                    is_global=is_global,
                    value=ast.Literal(1 if t.val.upper() == "ONLY"
                                      else 0)))
                return stmt
            self.expect_word("ISOLATION")
            self.expect_word("LEVEL")
            words = [self.next().val.upper()]
            if words[0] in ("READ", "REPEATABLE"):
                words.append(self.next().val.upper())
            level = " ".join(words)
            if level not in ("READ UNCOMMITTED", "READ COMMITTED",
                             "REPEATABLE READ", "SERIALIZABLE"):
                raise ParseError(f"bad isolation level {level}",
                                 self.peek())
            stmt.assignments.append(ast.VarAssignment(
                name="tx_isolation", is_system=True, is_global=is_global,
                value=ast.Literal(level.replace(" ", "-"))))
            return stmt
        while True:
            va = ast.VarAssignment(name="")
            if self.try_kw("GLOBAL"):
                va.is_global = True
                va.is_system = True
                va.name = self.ident()
            elif self.try_kw("SESSION") or self.try_word("LOCAL"):
                va.is_system = True
                va.name = self.ident()
            elif self.try_op("@"):
                if self.try_op("@"):
                    va.is_system = True
                    # @@global.x / @@session.x / @@local.x / @@x
                    nm = self.ident()
                    if nm in ("global", "session", "local") and \
                            self.try_op("."):
                        va.is_global = nm == "global"
                        nm = self.ident()
                    va.name = nm
                else:
                    va.name = "@" + self.ident()
            else:
                va.is_system = True
                va.name = self.ident()
            if not (self.try_op("=") or self.try_op(":=")):
                raise ParseError("expected =", self.peek())
            va.value = self.expr()
            stmt.assignments.append(va)
            if not self.try_op(","):
                return stmt

    def show(self) -> ast.ShowStmt:
        self.expect_kw("SHOW")
        s = ast.ShowStmt()
        if self.try_kw("GLOBAL"):
            s.is_global = True
        else:
            self.try_kw("SESSION")
        s.full = self.try_kw("FULL")
        if self.try_kw("DATABASES") or self.try_kw("SCHEMA"):
            s.tp = "databases"
        elif self.try_kw("TABLES"):
            s.tp = "tables"
            if self.try_kw("FROM"):
                s.db = self.ident()
        elif self.try_kw("CREATE"):
            self.expect_kw("TABLE")
            s.tp = "create_table"
            s.table = self.table_name()
        elif self.try_kw("COLUMNS") or self.try_kw("FIELDS"):
            s.tp = "columns"
            if not (self.try_kw("FROM") or self.try_kw("IN")):
                raise ParseError("expected FROM", self.peek())
            s.table = self.table_name()
        elif self.try_kw("INDEX", "KEY"):
            s.tp = "index"
            self.try_kw("FROM", "IN")
            s.table = self.table_name()
        elif self.peek().tp == TokenType.IDENT and \
                self.peek().val.upper() in ("INDEXES", "KEYS"):
            self.next()
            s.tp = "index"
            self.try_kw("FROM", "IN")
            s.table = self.table_name()
        elif self.peek().tp == TokenType.IDENT and \
                self.peek().val.upper() == "GRANTS":
            self.next()
            s.tp = "grants"
            if self.try_kw("FOR"):
                if self.peek().val.upper() == "CURRENT_USER":
                    self.next()
                    if self.try_op("("):
                        self.expect_op(")")
                else:
                    spec = self._user_spec()
                    s.pattern = f"{spec.user}@{spec.host}"
        elif self.try_kw("VARIABLES"):
            s.tp = "variables"
        elif self.peek().tp == TokenType.IDENT and \
                self.peek().val.upper() == "PROCESSLIST":
            self.next()
            s.tp = "processlist"
        elif self.try_kw("STATUS"):
            s.tp = "status"
        elif self.try_kw("ENGINES"):
            s.tp = "engines"
        elif self.try_kw("COLLATION"):
            s.tp = "collation"
        elif self.peek_word() == "CHARACTER" and \
                self.peek_word(1) == "SET":
            self.next()
            self.next()
            s.tp = "charset"
        elif self.try_kw("CHARSET"):
            s.tp = "charset"
        elif self.peek_word() in ("STATS_META", "STATS_HISTOGRAMS",
                                  "STATS_BUCKETS"):
            s.tp = self.next().val.lower()
        elif self.peek_word() in ("WARNINGS", "ERRORS", "PLUGINS",
                                  "PROFILES", "TRIGGERS", "EVENTS",
                                  "MASTER"):
            word = self.next().val.lower()
            if word == "master":
                self.expect_kw("STATUS")
                word = "master_status"
            s.tp = word
        elif self.peek_word() in ("PROCEDURE", "FUNCTION") and \
                self.peek(1).is_kw("STATUS"):
            w = self.next().val.lower()
            self.next()
            s.tp = f"{w}_status"
        else:
            raise ParseError("unsupported SHOW", self.peek())
        if self.try_kw("LIKE"):
            t = self.next()
            s.pattern = t.val
        elif self.try_kw("WHERE"):
            s.where = self.expr()
        return s

    # -- expressions (Pratt-ish precedence ladder) --------------------------

    def expr(self) -> ast.ExprNode:
        self.depth += 1
        if self.depth > MAX_EXPR_DEPTH:
            raise ParseError("expression too deeply nested", self.peek())
        try:
            return self.or_expr()
        finally:
            self.depth -= 1

    def or_expr(self):
        left = self.xor_expr()
        while True:
            if self.try_kw("OR") or self.try_op("||"):
                left = ast.BinaryOp("OR", left, self.xor_expr())
            else:
                return left

    def xor_expr(self):
        left = self.and_expr()
        while self.try_kw("XOR"):
            left = ast.BinaryOp("XOR", left, self.and_expr())
        return left

    def and_expr(self):
        left = self.not_expr()
        while True:
            if self.try_kw("AND") or self.try_op("&&"):
                left = ast.BinaryOp("AND", left, self.not_expr())
            else:
                return left

    def not_expr(self):
        if self.try_kw("NOT"):
            return ast.UnaryOp("NOT", self.not_expr())
        return self.predicate()

    def predicate(self):
        left = self.bit_or_expr()
        while True:
            t = self.peek()
            if t.tp == TokenType.OP and t.val in _CMP_OPS:
                self.next()
                qt = self.peek()
                if qt.tp in (TokenType.IDENT, TokenType.KEYWORD) and \
                        qt.val.upper() in ("ANY", "SOME", "ALL") and \
                        self.peek(1).tp == TokenType.OP and \
                        self.peek(1).val == "(":
                    if t.val == "<=>":
                        raise ParseError(
                            "<=> cannot be quantified with ANY/ALL", t)
                    self.next()
                    self.expect_op("(")
                    sub = self.select_or_union()
                    self.expect_op(")")
                    left = ast.QuantSubquery(
                        expr=left, op=t.val,
                        quant="all" if qt.val.upper() == "ALL" else "any",
                        select=sub)
                    continue
                left = ast.BinaryOp(t.val, left, self.bit_or_expr())
                continue
            if t.is_kw("IS"):
                self.next()
                neg = self.try_kw("NOT")
                if self.try_kw("NULL"):
                    left = ast.IsNullExpr(expr=left, negated=neg)
                elif self.try_kw("TRUE"):
                    # null-safe desugar: x IS TRUE == IFNULL(x,0) <> 0
                    # (a plain '= 1' would yield NULL for NULL, not 0)
                    e = ast.BinaryOp("<>", ast.FuncCall(
                        name="IFNULL", args=[left, ast.Literal(0)]),
                        ast.Literal(0))
                    left = ast.UnaryOp("NOT", e) if neg else e
                elif self.try_kw("FALSE"):
                    # x IS FALSE == IFNULL(x,1) = 0
                    e = ast.BinaryOp("=", ast.FuncCall(
                        name="IFNULL", args=[left, ast.Literal(1)]),
                        ast.Literal(0))
                    left = ast.UnaryOp("NOT", e) if neg else e
                else:
                    raise ParseError("expected NULL/TRUE/FALSE", self.peek())
                continue
            neg = False
            j = self.i
            if t.is_kw("NOT"):
                self.next()
                neg = True
                t = self.peek()
            if t.is_kw("IN"):
                self.next()
                self.expect_op("(")
                if self.peek().is_kw("SELECT"):
                    sub = self.select_or_union()
                    self.expect_op(")")
                    left = ast.InExpr(expr=left,
                                      items=ast.SubqueryExpr(select=sub),
                                      negated=neg)
                else:
                    items = [self.expr()]
                    while self.try_op(","):
                        items.append(self.expr())
                    self.expect_op(")")
                    left = ast.InExpr(expr=left, items=items, negated=neg)
                continue
            if t.is_kw("BETWEEN"):
                self.next()
                low = self.bit_or_expr()
                self.expect_kw("AND")
                high = self.bit_or_expr()
                left = ast.BetweenExpr(expr=left, low=low, high=high,
                                       negated=neg)
                continue
            if t.is_kw("LIKE"):
                self.next()
                pat = self.bit_or_expr()
                esc = "\\"
                if self.try_word("ESCAPE"):
                    et = self.next()
                    if et.tp != TokenType.STRING or len(et.val) > 1:
                        raise ParseError(
                            "ESCAPE must be a one-character string", et)
                    esc = et.val
                left = ast.LikeExpr(expr=left, pattern=pat, negated=neg,
                                    escape=esc)
                continue
            if t.tp in (TokenType.IDENT, TokenType.KEYWORD) and \
                    t.val.upper() in ("REGEXP", "RLIKE"):
                self.next()
                fc = ast.FuncCall(name="REGEXP_LIKE",
                                  args=[left, self.bit_or_expr()])
                left = ast.UnaryOp("NOT", fc) if neg else fc
                continue
            if neg:
                self.i = j  # lone NOT belongs to a higher level
            return left

    def bit_or_expr(self):
        left = self.bit_and_expr()
        while self.peek().tp == TokenType.OP and self.peek().val == "|":
            self.next()
            left = ast.BinaryOp("|", left, self.bit_and_expr())
        return left

    def bit_and_expr(self):
        left = self.shift_expr()
        while self.peek().tp == TokenType.OP and self.peek().val == "&":
            self.next()
            left = ast.BinaryOp("&", left, self.shift_expr())
        return left

    def shift_expr(self):
        left = self.add_expr()
        while self.peek().tp == TokenType.OP and self.peek().val in ("<<", ">>"):
            op = self.next().val
            left = ast.BinaryOp(op, left, self.add_expr())
        return left

    def add_expr(self):
        left = self.mul_expr()
        while self.peek().tp == TokenType.OP and self.peek().val in ("+", "-"):
            op = self.next().val
            if self.peek().is_kw("INTERVAL"):
                # expr +/- INTERVAL n UNIT (TPC-H date arithmetic)
                self.next()
                left = ast.FuncCall(
                    name="DATE_SUB" if op == "-" else "DATE_ADD",
                    args=[left, self._interval_expr()])
                continue
            left = ast.BinaryOp(op, left, self.mul_expr())
        return left

    def mul_expr(self):
        left = self.bitxor_expr()
        while True:
            t = self.peek()
            if t.tp == TokenType.OP and t.val in ("*", "/", "%"):
                self.next()
                left = ast.BinaryOp(t.val, left, self.bitxor_expr())
            elif t.is_kw("DIV") or t.is_kw("MOD"):
                self.next()
                left = ast.BinaryOp(t.val, left, self.bitxor_expr())
            else:
                return left

    def bitxor_expr(self):
        # bitwise ^ binds tighter than * (MySQL precedence), unlike | and &
        left = self.unary_expr()
        while self.peek().tp == TokenType.OP and self.peek().val == "^":
            self.next()
            left = ast.BinaryOp("^", left, self.unary_expr())
        return left

    def unary_expr(self):
        t = self.peek()
        if t.is_kw("BINARY") and not (
                self.peek(1).tp == TokenType.OP and
                self.peek(1).val in (")", ",")):
            # BINARY expr: collation cast — a no-op here, comparisons
            # are utf8_bin everywhere (docs/DEVIATIONS.md)
            self.next()
            return self.unary_expr()
        if t.tp == TokenType.OP and t.val in ("-", "+", "~", "!"):
            self.next()
            if t.val == "+":
                return self.unary_expr()
            if t.val == "!":
                return ast.UnaryOp("NOT", self.unary_expr())
            return ast.UnaryOp(t.val, self.unary_expr())
        return self.primary()

    def primary(self) -> ast.ExprNode:
        t = self.peek()
        if t.tp == TokenType.INT:
            self.next()
            return ast.Literal(int(t.val))
        if t.tp == TokenType.DECIMAL:
            self.next()
            return ast.Literal(decimal.Decimal(t.val))
        if t.tp == TokenType.FLOAT:
            self.next()
            return ast.Literal(float(t.val))
        if t.tp == TokenType.STRING:
            self.next()
            return ast.Literal(t.val)
        if t.tp == TokenType.OP and t.val == "(":
            self.next()
            if self.peek().is_kw("SELECT"):
                sub = self.select_or_union()
                self.expect_op(")")
                return ast.SubqueryExpr(select=sub)
            e = self.expr()
            if self.try_op(","):
                items = [e, self.expr()]
                while self.try_op(","):
                    items.append(self.expr())
                self.expect_op(")")
                return ast.RowExpr(items=items)
            self.expect_op(")")
            return e
        if t.tp == TokenType.OP and t.val == "@":
            self.next()
            if self.try_op("@"):
                nm = self.ident()
                is_global = False
                if nm in ("global", "session") and self.try_op("."):
                    is_global = nm == "global"
                    nm = self.ident()
                return ast.VariableExpr(name=nm, is_global=is_global,
                                        is_system=True)
            nm = self.ident()
            if self.try_op(":="):
                # @v := expr — assignment in expression position; MySQL
                # gives := the lowest precedence, so take a full expr
                return ast.VarAssignExpr(name=nm, value=self.expr())
            return ast.VariableExpr(name=nm)
        if t.tp == TokenType.OP and t.val == "?":
            self.next()
            return ast.ParamMarker()
        if t.tp == TokenType.KEYWORD:
            return self._keyword_primary(t)
        if t.tp == TokenType.IDENT:
            return self._ident_primary()
        raise ParseError("expected expression", t)

    def _keyword_primary(self, t: Token) -> ast.ExprNode:
        kw = t.val
        if kw == "NULL":
            self.next()
            return ast.Literal(None)
        if kw == "TRUE":
            self.next()
            return ast.Literal(1)
        if kw == "FALSE":
            self.next()
            return ast.Literal(0)
        if kw == "CASE":
            return self.case_expr()
        if kw in ("CAST", "CONVERT"):
            self.next()
            self.expect_op("(")
            e = self.expr()
            if kw == "CAST":
                self.expect_kw("AS")
                ft = self.cast_type()
            else:
                self.expect_op(",")
                ft = self.cast_type()
            self.expect_op(")")
            return ast.CastExpr(expr=e, ft=ft)
        if kw == "EXISTS":
            self.next()
            self.expect_op("(")
            sub = self.select_or_union()
            self.expect_op(")")
            return ast.ExistsSubquery(select=sub)
        if kw == "INTERVAL":
            if self.peek(1).tp == TokenType.OP and self.peek(1).val == "(":
                # INTERVAL(n, a1, a2, ...) — the compare function
                self.next()
                return self.func_call(kw)
            # INTERVAL n DAY — only inside date_add/sub handled there
            raise ParseError("INTERVAL outside date arithmetic", t)
        if kw in ("IF", "IFNULL", "COALESCE", "NULLIF", "REPLACE", "LEFT",
                  "RIGHT", "YEAR", "DATE", "TIME", "DEFAULT", "DATABASE",
                  "CHARSET", "MOD", "TRUNCATE"):
            # keyword-named functions
            if self.peek(1).tp == TokenType.OP and self.peek(1).val == "(":
                self.next()
                return self.func_call(kw)
        if kw in ("DISTINCT",):
            raise ParseError("unexpected DISTINCT", t)
        if kw in ("DATE", "TIMESTAMP", "TIME") and \
                self.peek(1).tp == TokenType.STRING:
            # typed literal: DATE '1998-12-01'
            self.next()
            return ast.Literal(self.next().val)
        return self._ident_primary()

    def case_expr(self) -> ast.CaseExpr:
        self.expect_kw("CASE")
        operand = None
        if not self.peek().is_kw("WHEN"):
            operand = self.expr()
        whens = []
        while self.try_kw("WHEN"):
            c = self.expr()
            self.expect_kw("THEN")
            whens.append((c, self.expr()))
        els = None
        if self.try_kw("ELSE"):
            els = self.expr()
        self.expect_kw("END")
        return ast.CaseExpr(operand=operand, when_clauses=whens,
                            else_clause=els)

    def cast_type(self) -> st.FieldType:
        t = self.next()
        name = t.val
        TC = st.TypeCode
        flen = frac = -1
        if self.try_op("("):
            flen = self._int_lit()
            if self.try_op(","):
                frac = self._int_lit()
            self.expect_op(")")
        if name in ("SIGNED", "INT", "INTEGER"):
            self.try_kw("INTEGER") or self.try_kw("INT")
            return st.new_int_field()
        if name == "UNSIGNED":
            self.try_kw("INTEGER") or self.try_kw("INT")
            return st.new_uint_field()
        if name in ("DECIMAL", "NUMERIC"):
            return st.new_decimal_field(flen if flen > 0 else 10,
                                        frac if frac >= 0 else 0)
        if name in ("CHAR", "BINARY"):
            if self.peek_word() == "CHARACTER" and \
                    self.peek_word(1) == "SET":
                self.next()
                self.next()
                self.next()        # charset name: accepted, fixed utf8
            return st.new_string_field(flen if flen > 0 else 255)
        if name in ("DOUBLE", "REAL", "FLOAT"):
            return st.new_double_field()
        if name == "DATE":
            return st.new_date_field()
        if name == "DATETIME":
            return st.new_datetime_field()
        if name == "TIME":
            return st.new_duration_field()
        if name == "JSON":
            return st.FieldType(TC.JSON)
        raise ParseError(f"unsupported cast type {name}", t)

    def _ident_primary(self) -> ast.ExprNode:
        name = self.ident()
        # function call?
        if self.peek().tp == TokenType.OP and self.peek().val == "(":
            return self.func_call(name.upper())
        # qualified column
        if self.try_op("."):
            b = self.ident()
            if self.try_op("."):
                return ast.ColName(name=self.ident(), table=b, db=name)
            return ast.ColName(name=b, table=name)
        return ast.ColName(name=name)

    def func_call(self, name: str) -> ast.ExprNode:
        self.expect_op("(")
        if name == "EXTRACT":
            # EXTRACT(unit FROM e) desugars to the field functions
            return self._extract_expr()
        if name in ("SUBSTRING", "SUBSTR", "MID"):
            # SUBSTRING(s FROM pos [FOR len]) == SUBSTRING(s, pos[, len])
            first = self.expr()
            args = [first]
            if self.try_kw("FROM"):
                args.append(self.expr())
                if self.try_kw("FOR"):
                    args.append(self.expr())
            else:
                while self.try_op(","):
                    args.append(self.expr())
            self.expect_op(")")
            return ast.FuncCall(name="SUBSTRING", args=args)
        if name == "GET_FORMAT":
            # first argument is a bare DATE/TIME/DATETIME/TIMESTAMP word
            ut = self.next()
            if ut.tp not in (TokenType.IDENT, TokenType.KEYWORD):
                raise ParseError("expected DATE/TIME/DATETIME", ut)
            self.expect_op(",")
            loc = self.expr()
            self.expect_op(")")
            return ast.FuncCall(name="GET_FORMAT",
                                args=[ast.Literal(ut.val.upper()), loc])
        if name in ("TIMESTAMPDIFF", "TIMESTAMPADD"):
            # first argument is a bare unit word, not an expression
            ut = self.next()
            if ut.tp not in (TokenType.IDENT, TokenType.KEYWORD):
                raise ParseError("expected time unit", ut)
            unit = ut.val.upper()
            self.expect_op(",")
            a1 = self.expr()
            self.expect_op(",")
            a2 = self.expr()
            self.expect_op(")")
            if name == "TIMESTAMPADD":
                return ast.FuncCall(name="DATE_ADD", args=[
                    a2, ast.FuncCall(name="INTERVAL",
                                     args=[a1, ast.Literal(unit)])])
            return ast.FuncCall(name="TIMESTAMPDIFF",
                                args=[ast.Literal(unit), a1, a2])
        if name in _AGG_FUNCS:
            distinct = self.try_kw("DISTINCT")
            if self.try_op("*"):
                self.expect_op(")")
                return ast.AggregateCall(name=name, star=True)
            args = [self.expr()]
            while self.try_op(","):
                args.append(self.expr())
            sep = ","
            if name == "GROUP_CONCAT" and \
                    self.peek().tp == TokenType.IDENT and \
                    self.peek().val.upper() == "SEPARATOR":
                self.next()
                sep = self._str_lit()
            self.expect_op(")")
            return ast.AggregateCall(name=name, args=args,
                                     distinct=distinct, sep=sep)
        args = []
        if not self.try_op(")"):
            # DATE_ADD(d, INTERVAL n DAY)
            while True:
                if self.peek().is_kw("INTERVAL") and not (
                        self.peek(1).tp == TokenType.OP and
                        self.peek(1).val == "("):
                    # DATE_ADD(d, INTERVAL n DAY); INTERVAL( stays the
                    # compare function and parses as a normal expr
                    self.next()
                    args.append(self._interval_expr())
                else:
                    args.append(self.expr())
                if not self.try_op(","):
                    break
            self.expect_op(")")
        return ast.FuncCall(name=name, args=args)

    def _extract_expr(self) -> ast.ExprNode:
        ut = self.next()
        if ut.tp not in (TokenType.IDENT, TokenType.KEYWORD):
            raise ParseError("expected time unit", ut)
        unit = ut.val.upper()
        self.expect_kw("FROM")
        e = self.expr()
        self.expect_op(")")
        if unit in ("YEAR", "MONTH", "DAY", "HOUR", "MINUTE", "SECOND",
                    "WEEK", "QUARTER", "MICROSECOND"):
            return ast.FuncCall(name=unit, args=[e])
        if unit == "YEAR_MONTH":
            return ast.BinaryOp("+", ast.BinaryOp(
                "*", ast.FuncCall(name="YEAR", args=[e]),
                ast.Literal(100)), ast.FuncCall(name="MONTH", args=[e]))
        raise ParseError(f"unsupported EXTRACT unit {unit}", ut)

    def _interval_expr(self) -> ast.FuncCall:
        """`n UNIT` after a consumed INTERVAL keyword."""
        n = self.expr()
        unit = self.ident().upper()
        return ast.FuncCall(name="INTERVAL", args=[n, ast.Literal(unit)])

    def column_name(self) -> ast.ColName:
        a = self.ident()
        if self.try_op("."):
            b = self.ident()
            if self.try_op("."):
                return ast.ColName(name=self.ident(), table=b, db=a)
            return ast.ColName(name=b, table=a)
        return ast.ColName(name=a)
